"""mxnet_tpu.serving.operator — the fleet operates itself.

The serving stack self-heals (fleet.py) and self-diagnoses
(observability: per-executable cost ledger, multi-window SLO burn
rates, correlated incidents), and this module is the layer that ACTS
on those signals (docs/serving.md "Fleet operations"):

- :class:`Autoscaler` — a control loop scaling replica counts per
  ``model@variant`` group from two signals: measured queue depth per
  healthy replica and the alert engine's open SLO-burn incidents.
  Scale-down reuses the HEALTHY → ``DRAINING(scale)`` → DEAD drain
  machinery so in-flight requests always complete; scale-up mints
  replicas warm from the AOT compile cache and admits them only after
  every declared bucket executable is built and a health probe passes
  (load-bound, never compile-bound). Distinct up/down thresholds plus
  per-direction cooldowns give the loop hysteresis — a flapping signal
  (chaos kind ``autoscale_flap``) is bounded, not amplified.
- :class:`RolloutManager` — zero-downtime canaried artifact rollout
  with instant rollback. A candidate artifact (a params dict, or a
  PR-15 autotune schedule table) is applied to ONE canary replica
  first and must pass three gates before fleet-wide promotion:
  (1) health — canary outputs on the eval batch are finite;
  (2) accuracy — ``parity_sweep.py``-style top-1 agreement against the
  prior artifact (or a caller-supplied reference) at or above
  ``MXNET_TPU_ROLLOUT_MIN_AGREEMENT``;
  (3) latency — canary p50 over ``MXNET_TPU_ROLLOUT_CANARY_CALLS``
  requests within ``MXNET_TPU_ROLLOUT_MAX_LATENCY_X`` x the measured
  pre-rollout baseline.
  Any gate failure restores the prior artifact on the canary before
  returning — the rest of the fleet never saw the candidate, so a bad
  push (chaos kind ``rollout_bad_weights``) or a slow one
  (``canary_slo_regression``) costs zero client-visible errors.

Weight promotion is an atomic in-place value swap under each
predictor's lock (``Predictor.swap_params``): param values are runtime
operands, not part of the AOT fingerprint, so every compiled bucket
executable stays live — no retrace, no recompile, no dropped request.
A schedule-table rollout IS an executable change, so it goes through
the front door instead: the table swaps via ``MXNET_TPU_SCHEDULE_TABLE``,
``capture.note_recapture`` records the structured retrace reason, and
each replica rebuilds its bucket set from the (pre-seeded) AOT cache.

Every decision — scale up/down/hold, promote, rollback, hold — is a
flight-recorder event (kind ``operator``) plus a counter in
``serving.stats()``; every rollout is one span tree rooted at
``rollout.weights`` / ``rollout.schedule``, so an incident opened while
a rollout is in flight correlates to it by trace id and by the flight
slice embedded in the incident.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

from ..base import MXNetError
from ..observability import flight as _obs_flight
from ..observability import trace as _trace
from ..resilience import faults as _faults
from . import _STATS
from .fleet import _variant_key


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------------- autoscaler

class Autoscaler:
    """SLO-burn + queue-depth driven replica autoscaling for one Fleet.

    Synchronous core: ``evaluate()`` reads the signals once and issues
    at most one scaling action per replica group, returning the
    decision records. ``start()`` runs that loop on a daemon thread
    every ``interval_s``. Decisions:

    - scale UP when an SLO-burn incident (``slo_deadline_burn`` /
      ``slo_shed_burn``) is open for the fleet OR queue depth per
      healthy replica reaches ``up_queue`` — by ``step`` replicas, to
      at most ``max_replicas``.
    - scale DOWN when queue depth is at or under ``down_queue`` AND no
      burn incident is open — by one replica, to at least
      ``min_replicas``. The supervisor drains the least-loaded member
      (``DRAINING(scale)``): in-flight requests complete, and the
      leaver never counts against the alert engine's healthy floor.
    - HOLD otherwise — still a recorded decision (flight event kind
      ``operator`` + the ``fleet_scale_hold`` counter), so a quiet
      control loop is distinguishable from a dead one.

    Hysteresis: the up/down thresholds are distinct, and each direction
    has its own ``cooldown_s`` window per group — additionally a
    scale-DOWN is refused inside the cooldown window of the last
    scale-UP, so an oscillating signal (chaos ``autoscale_flap``)
    causes at most one scale event per cooldown period instead of
    thrashing the fleet.
    """

    def __init__(self, fleet, *, min_replicas=None, max_replicas=None,
                 up_queue=None, down_queue=None, cooldown_s=None,
                 step=None, interval_s=None, clock=time.monotonic):
        self._fleet = fleet
        self.min_replicas = max(1, int(
            min_replicas if min_replicas is not None
            else _env_int("MXNET_TPU_FLEET_MIN_REPLICAS", 1)))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env_int("MXNET_TPU_FLEET_MAX_REPLICAS", 8))
        self.up_queue = float(
            up_queue if up_queue is not None
            else _env_float("MXNET_TPU_FLEET_SCALE_UP_QUEUE", 8.0))
        self.down_queue = float(
            down_queue if down_queue is not None
            else _env_float("MXNET_TPU_FLEET_SCALE_DOWN_QUEUE", 1.0))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else _env_float("MXNET_TPU_FLEET_SCALE_COOLDOWN_S", 30.0))
        self.step = max(1, int(
            step if step is not None
            else _env_int("MXNET_TPU_FLEET_SCALE_STEP", 1)))
        self.interval_s = float(
            interval_s if interval_s is not None
            else _env_float("MXNET_TPU_FLEET_SCALE_INTERVAL_S", 2.0))
        if self.down_queue >= self.up_queue:
            raise MXNetError(
                f"Autoscaler needs down_queue < up_queue for hysteresis, "
                f"got {self.down_queue} >= {self.up_queue}")
        self._clock = clock
        self._last = {}            # (group, "up"|"down") -> decision time
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- signals
    def _burning(self, rule_ids=("slo_deadline_burn", "slo_shed_burn")):
        """True when the alert engine holds an OPEN SLO-burn incident —
        the operator consumes the engine's multi-window judgement
        instead of re-deriving burn math from raw counters."""
        from ..observability import alerts as _alerts

        try:
            for inc in _alerts.open_incidents():
                if inc.get("rule") in rule_ids:
                    return True
        except Exception:
            pass
        return False

    def signals(self, group):
        """Measured load signals for one replica group: queue depth per
        healthy replica (router-outstanding, the same number the
        balancer minimizes) and the in-fleet member count."""
        members = [r for r in self._fleet._sup.replicas(group)
                   if not r.scale_drain]
        healthy = [r for r in members if r.state == "HEALTHY"]
        queued = sum(r.outstanding for r in healthy)
        depth = queued / max(1, len(healthy))
        return {"members": len(members), "healthy": len(healthy),
                "queue_per_replica": depth}

    # ------------------------------------------------------------ decisions
    def _cooled(self, now, group, direction):
        t = self._last.get((group, direction))
        return t is None or (now - t) >= self.cooldown_s

    def evaluate(self, now=None):
        """One control-loop pass over every replica group; returns the
        decision records (also flight events + counters). ``now`` takes
        a synthetic clock for deterministic tests."""
        now = self._clock() if now is None else now
        burning = self._burning()
        decisions = []
        with self._lock:
            for group in self._fleet.models():
                sig = self.signals(group)
                depth = _faults.maybe_autoscale_flap(
                    sig["queue_per_replica"])
                count = sig["members"]
                action, target = "hold", count
                if ((burning or depth >= self.up_queue)
                        and count < self.max_replicas
                        and self._cooled(now, group, "up")):
                    action = "scale_up"
                    target = min(self.max_replicas, count + self.step)
                elif (not burning and depth <= self.down_queue
                        and count > self.min_replicas
                        and self._cooled(now, group, "up")
                        and self._cooled(now, group, "down")):
                    action = "scale_down"
                    target = max(self.min_replicas, count - 1)
                decision = {"group": group, "action": action,
                            "from": count, "to": target,
                            "queue_per_replica": round(float(depth), 3),
                            "slo_burn": burning}
                if action == "hold":
                    _STATS["fleet_scale_hold"] += 1
                else:
                    self._last[(group, "up" if action == "scale_up"
                                else "down")] = now
                _obs_flight.record("operator", decide=action, model=group,
                                   replicas=count, target=target,
                                   queue=round(float(depth), 3),
                                   slo_burn=burning)
                if action != "hold":
                    try:
                        decision["to"] = self._fleet.scale_to(
                            target, model=group)
                    except Exception as e:
                        decision["error"] = str(e)
                        _obs_flight.record("operator", decide="error",
                                           model=group, error=str(e))
                decisions.append(decision)
        return decisions

    # ----------------------------------------------------------- background
    def start(self):
        """Run the control loop on a daemon thread every
        ``interval_s``; idempotent."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-tpu-autoscaler",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                # the control loop must survive a transient read racing
                # fleet teardown; the next tick sees consistent state
                pass

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    close = stop


# --------------------------------------------------------------- rollouts

class RolloutManager:
    """Canaried zero-downtime artifact rollout for one replica group.

    Thread-mode fleets only: a live param swap needs the predictor in
    this process (process replicas rebuild through their factory
    instead). ``eval_batch`` — one representative input batch (array or
    dict name -> array, WITH batch axis) — drives all three gates; give
    it at construction or per call.
    """

    def __init__(self, fleet, *, model="default", variant=None,
                 eval_batch=None, min_agreement=None, canary_calls=None,
                 max_latency_x=None):
        self._fleet = fleet
        self._group = _variant_key(model, variant)
        self._eval_batch = eval_batch
        self.min_agreement = float(
            min_agreement if min_agreement is not None
            else _env_float("MXNET_TPU_ROLLOUT_MIN_AGREEMENT", 0.99))
        self.canary_calls = max(1, int(
            canary_calls if canary_calls is not None
            else _env_int("MXNET_TPU_ROLLOUT_CANARY_CALLS", 16)))
        self.max_latency_x = float(
            max_latency_x if max_latency_x is not None
            else _env_float("MXNET_TPU_ROLLOUT_MAX_LATENCY_X", 3.0))
        self._seq = itertools.count(1)
        self._lock = threading.Lock()

    # -------------------------------------------------------------- helpers
    def _members(self):
        if self._fleet.mode != "thread":
            raise MXNetError(
                "rollout needs a thread-mode fleet (process replicas "
                "own their predictor in a child; roll out by updating "
                "the factory artifact and restarting instead)")
        members = sorted(
            (r for r in self._fleet._sup.replicas(self._group)
             if r.state == "HEALTHY" and not r.scale_drain),
            key=lambda r: r.rid)
        if not members:
            raise MXNetError(
                f"rollout: no HEALTHY replica in group "
                f"'{self._group}' to canary on")
        return members

    def _batch(self, eval_batch):
        batch = eval_batch if eval_batch is not None else self._eval_batch
        if batch is None:
            raise MXNetError(
                "rollout needs an eval_batch (constructor or call) to "
                "drive the canary gates")
        return batch

    @staticmethod
    def _finite(outs):
        import numpy as np

        for o in outs:
            if not np.all(np.isfinite(np.asarray(o))):
                return False
        return True

    @staticmethod
    def _agreement(cand, ref):
        """parity_sweep.py-style accuracy gate: top-1 agreement between
        candidate and reference outputs when the trailing axis is a
        class axis; element-wise closeness fraction otherwise."""
        import numpy as np

        a = np.asarray(cand[0])
        b = np.asarray(ref[0])
        if a.shape != b.shape:
            return 0.0
        if a.ndim >= 2 and a.shape[-1] > 1:
            return float(np.mean(np.argmax(a, axis=-1)
                                 == np.argmax(b, axis=-1)))
        return float(np.mean(np.isclose(a, b, rtol=1e-2, atol=1e-5)))

    def _measure_p50(self, pred, batch, faulted=False):
        """Canary latency window: p50 over ``canary_calls`` direct
        predictor calls. ``faulted`` routes each sample through the
        ``canary_slo_regression`` chaos hook (candidate windows only —
        the baseline must stay honest)."""
        lat = []
        for _ in range(self.canary_calls):
            t0 = time.perf_counter()
            pred.predict_raw(batch)
            dt = time.perf_counter() - t0
            if faulted:
                dt = _faults.maybe_canary_slo_regression(dt)
            lat.append(dt)
        lat.sort()
        return lat[len(lat) // 2]

    # The weights and schedule paths share one span tree shape; each
    # shared span literal lives at ONE site (graftlint RD004: a span
    # name must identify one site per module).
    @staticmethod
    def _canary_span(replica):
        return _trace.span("rollout.canary", replica=replica.rid)

    def _latency_gate(self, pred, batch, base_p50):
        """The shared latency gate: candidate p50 must stay within
        ``max_latency_x`` of the pre-swap baseline. Returns
        ``(gate, detail, p50)`` with ``gate`` None on pass."""
        with _trace.span("rollout.gate.latency"):
            p50 = self._measure_p50(pred, batch, faulted=True)
            ceil = max(base_p50, 1e-6) * self.max_latency_x
            if p50 > ceil:
                return ("latency",
                        f"canary p50 {p50 * 1e6:.0f}us > "
                        f"{self.max_latency_x}x baseline "
                        f"{base_p50 * 1e6:.0f}us", p50)
        return None, None, p50

    # ------------------------------------------------- decode canary gates
    @staticmethod
    def _decode_capable(pred):
        """Decode predictors duck-type ``greedy_decode``; fixed-shape
        predictors get the classic three gates only."""
        return hasattr(pred, "greedy_decode")

    @staticmethod
    def _decode_probe(pred):
        """Deterministic canary prompt + decode length, sized to the
        predictor's context window so the probe never trips the
        max_len eviction path."""
        spec = pred._spec
        prompt = [(i * 7 + 3) % spec["vocab"] for i in range(6)]
        return prompt, max(1, min(6, spec["max_len"] - len(prompt) - 1))

    def _measure_ttft(self, pred, prompt):
        """p50 time-to-first-token over a quarter canary window: each
        sample is one bucketed prefill + first-token emit
        (``greedy_decode`` of a single token) — the decode cost a real
        admission pays before it can stream anything."""
        lat = []
        for _ in range(max(1, self.canary_calls // 4)):
            t0 = time.perf_counter()
            pred.greedy_decode(list(prompt), 1)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return lat[len(lat) // 2]

    def _token_parity(self, pred, prompt, n):
        """Greedy-decode the candidate through the PAGED path and check
        every token against the argmax of the predictor's own flat
        full-context forward on the growing context. Internal
        consistency of one artifact across its two executable families
        — a candidate whose paged KV path diverges from its probe
        forward must not serve streams. Returns None on parity, else
        ``(index, total, got, want)`` for the first mismatch."""
        import numpy as np

        toks = pred.greedy_decode(list(prompt), n)
        ctx = list(prompt)
        for i, t in enumerate(toks):
            outs, _ = pred.predict_raw(np.asarray([ctx], np.int32))
            want = int(np.argmax(np.asarray(outs[0])[0, -1]))
            if int(t) != want:
                return i, len(toks), int(t), want
            ctx.append(want)
        return None

    def _decode_gates(self, pred, base_ttft):
        """The two extra gates a decode-capable canary must pass after
        the classic three: token parity, then TTFT p50 within
        ``max_latency_x`` of the pre-swap baseline. Returns
        ``(gate, detail, ttft)`` with ``gate`` None on pass."""
        prompt, n = self._decode_probe(pred)
        with _trace.span("rollout.gate.decode_parity"):
            mismatch = self._token_parity(pred, prompt, n)
            if mismatch is not None:
                i, total, got, want = mismatch
                return ("decode_parity",
                        f"paged token {i}/{total} = {got} but flat "
                        f"argmax = {want}", None)
        with _trace.span("rollout.gate.decode_ttft"):
            ttft = self._measure_ttft(pred, prompt)
            ceil = max(base_ttft, 1e-6) * self.max_latency_x
            if ttft > ceil:
                return ("decode_ttft",
                        f"canary TTFT p50 {ttft * 1e6:.0f}us > "
                        f"{self.max_latency_x}x baseline "
                        f"{base_ttft * 1e6:.0f}us", ttft)
        return None, None, ttft

    @staticmethod
    def _rollback_span(gate):
        return _trace.span("rollout.rollback", gate=gate)

    @staticmethod
    def _promote_span(replicas):
        return _trace.span("rollout.promote", replicas=replicas)

    def _decide(self, span, kind, rollout_id, action, **fields):
        key = {"promote": "rollout_promotions",
               "rollback": "rollout_rollbacks",
               "hold": "rollout_holds"}[action]
        _STATS[key] += 1
        span.set(outcome=action, **fields)
        _obs_flight.record("operator", decide=action, rollout=rollout_id,
                           artifact=kind, model=self._group, **fields)
        out = {"action": action, "rollout_id": rollout_id,
               "artifact": kind, "group": self._group}
        out.update(fields)
        return out

    # -------------------------------------------------------------- weights
    def rollout_weights(self, params, eval_batch=None, reference=None):
        """Canary-then-promote one candidate params artifact (dict
        ``name``/``arg:name``/``aux:name`` -> array, or a params file
        path). Returns the decision record: ``action`` is ``promote``
        or ``rollback`` (+ ``gate``/``detail`` naming the failed
        gate). ``reference`` optionally supplies the accuracy gate's
        expected outputs; default is the prior artifact's own outputs
        on the eval batch — right for a weight refresh that must not
        shift behavior, too strict for an intentional retrain (pass the
        new reference outputs then)."""
        batch = self._batch(eval_batch)
        with self._lock:
            rollout_id = f"weights-{next(self._seq)}"
            members = self._members()
            canary, rest = members[0], members[1:]
            with _trace.span("rollout.weights", rollout=rollout_id,
                             model=self._group, canary=canary.rid,
                             replicas=len(members)) as root:
                params = _faults.maybe_rollout_bad_weights(params)
                # Bind the canary's predictor OBJECT once: while the
                # candidate serves live traffic, a bad artifact can trip
                # the sentinel/breaker and the supervisor may recycle
                # the canary replica mid-rollout (replica.predictor
                # becomes None, then a fresh build). Gates and rollback
                # keep operating on the bound object — and a restart
                # rebuilds the pristine factory artifact, so unswapping
                # an orphaned predictor is harmless either way.
                pred = canary.predictor
                with self._canary_span(canary):
                    base_outs, _ = pred.predict_raw(batch)
                    base_p50 = self._measure_p50(pred, batch)
                    base_ttft = None
                    if self._decode_capable(pred):
                        base_ttft = self._measure_ttft(
                            pred, self._decode_probe(pred)[0])
                    try:
                        prev = pred.swap_params(params)
                    except MXNetError as e:
                        # rejected before any cell flipped: the prior
                        # artifact never left, but the push failed
                        return self._decide(
                            root, "weights", rollout_id, "rollback",
                            gate="swap_validation", detail=str(e))
                gate, detail = None, None
                with _trace.span("rollout.gate.health"):
                    cand_outs, _ = pred.predict_raw(batch)
                    if not self._finite(cand_outs):
                        gate, detail = "health", "nonfinite canary outputs"
                agreement = None
                if gate is None:
                    with _trace.span("rollout.gate.accuracy"):
                        ref = reference if reference is not None \
                            else base_outs
                        agreement = self._agreement(cand_outs, ref)
                        if agreement < self.min_agreement:
                            gate = "accuracy"
                            detail = (f"top-1 agreement {agreement:.4f} < "
                                      f"{self.min_agreement}")
                p50 = None
                if gate is None:
                    gate, detail, p50 = self._latency_gate(
                        pred, batch, base_p50)
                ttft = None
                if gate is None and base_ttft is not None:
                    gate, detail, ttft = self._decode_gates(
                        pred, base_ttft)
                if gate is not None:
                    with self._rollback_span(gate):
                        pred.swap_params(prev)
                    return self._decide(
                        root, "weights", rollout_id, "rollback",
                        gate=gate, detail=detail)
                with self._promote_span(len(rest) + 1):
                    for r in rest:
                        rp = r.predictor
                        if rp is None:
                            # recycled mid-promote: the restart rebuilds
                            # the factory artifact; the next rollout of
                            # the same candidate converges it
                            continue
                        rp.swap_params(params)
                fields = {"agreement": round(agreement, 4),
                          "canary_p50_us": int(p50 * 1e6),
                          "baseline_p50_us": int(base_p50 * 1e6)}
                if ttft is not None:
                    fields["canary_ttft_us"] = int(ttft * 1e6)
                    fields["baseline_ttft_us"] = int(base_ttft * 1e6)
                return self._decide(
                    root, "weights", rollout_id, "promote", **fields)

    # ------------------------------------------------------------- schedule
    def rollout_schedule(self, table_path, eval_batch=None, reason=None):
        """Canary-then-promote one PR-15 autotune schedule table. Unlike
        a weight swap this CHANGES the executables, so it rides the
        sanctioned retrace path: the table swaps in via
        ``MXNET_TPU_SCHEDULE_TABLE``, ``capture.note_recapture`` records
        the structured reason against the old/new schedule tokens, and
        each replica rebuilds its bucket set through ``warmup()`` —
        loaded from the AOT cache when the new table's artifacts were
        pre-seeded, compiled once here when not. The canary rebuilds and
        passes the latency window first; rollback restores the previous
        table env and rebuilds the canary from the still-cached old
        artifacts."""
        from .. import capture as _capture
        from ..tune import schedule as _schedule

        batch = self._batch(eval_batch)
        with self._lock:
            rollout_id = f"schedule-{next(self._seq)}"
            members = self._members()
            canary, rest = members[0], members[1:]
            with _trace.span("rollout.schedule", rollout=rollout_id,
                             model=self._group, canary=canary.rid,
                             table=str(table_path)) as root:
                import json

                try:
                    with open(table_path, encoding="utf-8") as f:
                        data = json.load(f)
                except (OSError, ValueError) as e:
                    return self._decide(
                        root, "schedule", rollout_id, "rollback",
                        gate="validation", detail=f"unreadable: {e}")
                problems = _schedule.validate_table(data)
                if problems:
                    return self._decide(
                        root, "schedule", rollout_id, "rollback",
                        gate="validation",
                        detail="; ".join(problems[:4]))
                old_env = os.environ.get("MXNET_TPU_SCHEDULE_TABLE")
                old_token = _schedule.fingerprint_token()
                # bound once, like rollout_weights: survives the
                # supervisor recycling a replica mid-rollout
                canary_pred = canary.predictor
                base_p50 = self._measure_p50(canary_pred, batch)
                base_ttft = None
                if self._decode_capable(canary_pred):
                    base_ttft = self._measure_ttft(
                        canary_pred, self._decode_probe(canary_pred)[0])

                def _swap_env(value):
                    if value is None:
                        os.environ.pop("MXNET_TPU_SCHEDULE_TABLE", None)
                    else:
                        os.environ["MXNET_TPU_SCHEDULE_TABLE"] = \
                            str(value)
                    _schedule.load_table(refresh=True)

                _swap_env(table_path)
                new_token = _schedule.fingerprint_token()
                if new_token == old_token:
                    # same measured schedules: nothing to recompile,
                    # nothing to canary; the env swap stands
                    return self._decide(
                        root, "schedule", rollout_id, "hold",
                        detail="schedule token unchanged")
                _capture.note_recapture(
                    f"serving_schedule:{self._group}", old_token,
                    new_token,
                    reason=reason or "autotune schedule rollout: "
                    "measured schedule table changed, bucket "
                    "executables rebuild under the new AOT key")

                def _rebuild(pred):
                    if pred is None:
                        # replica recycled mid-rollout: its restart
                        # already rebuilds under the live table env
                        return
                    with pred._lock:
                        pred._execs.clear()
                    pred.warmup()

                gate, detail = None, None
                with self._canary_span(canary):
                    try:
                        _rebuild(canary_pred)
                    except Exception as e:
                        gate, detail = "health", f"canary rebuild: {e}"
                p50 = None
                if gate is None:
                    gate, detail, p50 = self._latency_gate(
                        canary_pred, batch, base_p50)
                ttft = None
                if gate is None and base_ttft is not None:
                    gate, detail, ttft = self._decode_gates(
                        canary_pred, base_ttft)
                if gate is not None:
                    with self._rollback_span(gate):
                        _swap_env(old_env)
                        _capture.note_recapture(
                            f"serving_schedule:{self._group}", new_token,
                            old_token,
                            reason="schedule rollout rolled back: "
                            f"canary {gate} gate failed")
                        _rebuild(canary_pred)
                    return self._decide(
                        root, "schedule", rollout_id, "rollback",
                        gate=gate, detail=detail)
                with self._promote_span(len(rest) + 1):
                    for r in rest:
                        _rebuild(r.predictor)
                fields = {"old_token": old_token,
                          "new_token": new_token,
                          "canary_p50_us": int(p50 * 1e6),
                          "baseline_p50_us": int(base_p50 * 1e6)}
                if ttft is not None:
                    fields["canary_ttft_us"] = int(ttft * 1e6)
                    fields["baseline_ttft_us"] = int(base_ttft * 1e6)
                return self._decide(
                    root, "schedule", rollout_id, "promote", **fields)
