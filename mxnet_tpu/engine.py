"""Dependency-engine control: real op bulking over lazy segments.

Parity: python/mxnet/engine.py (bulk/set_bulk_size over the dependency
engine, include/mxnet/engine.h:311) and the bulk mode of
src/engine/threaded_engine.cc, where consecutive engine pushes are fused
into one kernel-launch burst. TPU-native mechanics:

- With a nonzero bulk size, eager op dispatch stops executing one cached
  XLA executable per op. Instead each call is *recorded* into the current
  thread's lazy segment and returns a `_Placeholder` — a symbolic cell value
  carrying only shape/dtype (inferred through `jax.eval_shape`, with an aval
  cache so steady-state recording is pure dict work).
- A segment is *forced* when it reaches the bulk size, when the `bulk`
  scope exits, or when any placeholder is read (`wait_to_read`, `asnumpy`,
  `__array__`, or any jax op consuming it via the `__jax_array__`
  protocol). Forcing traces the whole recorded segment and jit-compiles it
  as ONE executable, cached on the recorded (op, params, shape, dtype)
  sequence — so a steady-state training loop replays a compiled segment per
  `bulk_size` ops instead of dispatching each one.
- Bulking is bypassed (dispatch falls back to per-op eager) while autograd
  is recording or a jit.trace discovery pass is live: both capture concrete
  buffers per op and would observe placeholders otherwise.

When bulking helps: eager host-bound loops (optimizer updates over many
small parameters, metric/update chains) where per-op dispatch overhead
dominates. Inside `mx.jit.trace`/hybridize the whole step is already one
executable and bulking is a no-op by design. See docs/engine.md.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

__all__ = ["set_bulk_size", "bulk", "flush", "bulk_stats"]

_TLS = threading.local()

# Flat counters, merged into profiler.dumps() / profiler.dispatch_stats().
_STATS = {
    "bulk_segments": 0,
    "bulk_ops": 0,
    "bulk_cache_hit": 0,
    "bulk_cache_miss": 0,
    "bulk_max_segment": 0,
    "bulk_fallback_eager": 0,
}

# (device, recorded sequence) -> jitted segment executable
_SEG_CACHE: dict = {}
# (param key, input avals) -> (output is tuple?, flat output ShapeDtypeStructs)
_AVAL_CACHE: dict = {}
# np.dtype -> str; numpy's dtype.__str__ costs ~10us and sits on the
# per-record path
_DTYPE_STR: dict = {}


def _dtype_str(dt):
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR[dt] = str(dt)
    return s


def bulk_stats():
    return dict(_STATS)


def _state():
    st = _TLS
    if not hasattr(st, "size"):
        st.size = 0
        st.seg = None
    return st


class _Placeholder:
    """Symbolic value of an NDArray cell inside an unforced bulk segment.

    Reads force the owning segment: `__jax_array__` (any jax op consuming
    it), `__array__` (numpy / `asnumpy`), `block_until_ready`
    (`wait_to_read`). Unknown attribute access falls back to the concrete
    array, so stray direct-jnp paths degrade to a force instead of an error.
    """

    __slots__ = ("_seg", "_slot", "_aval", "__weakref__")

    def __init__(self, seg, slot, aval):
        self._seg = seg
        self._slot = slot
        self._aval = aval

    @property
    def shape(self):
        return self._aval.shape

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        n = 1
        for d in self._aval.shape:
            n *= d
        return n

    def _mxtpu_force(self):
        return self._seg.force()[self._slot]

    def __jax_array__(self):
        return self._mxtpu_force()

    def __array__(self, dtype=None):
        import numpy as np

        a = np.asarray(self._mxtpu_force())
        return a.astype(dtype) if dtype is not None else a

    def block_until_ready(self):
        v = self._mxtpu_force()
        v.block_until_ready()
        return v

    def __getitem__(self, idx):
        return self._mxtpu_force()[idx]

    def __getattr__(self, name):
        if name.startswith("__"):  # no dunder protocol via concrete fallback
            raise AttributeError(name)
        return getattr(self._mxtpu_force(), name)

    def __repr__(self):
        state = "resolved" if self._seg.results is not None else "lazy"
        return (f"<bulk placeholder {self._aval.shape} {self._aval.dtype} "
                f"[{state}]>")


class _Segment:
    """One recorded sequence of eager op calls, compiled and run as a unit."""

    def __init__(self, device):
        self.device = device
        self.entries = []      # (op, params, dyn_keys, descs, base, n_out)
        self.ext = []          # concrete external input arrays, in first use order
        self._ext_pos = {}     # id(array) -> position in ext
        self.avals = []        # flat output avals across all entries
        self.key_parts = []    # per-entry cache-key parts, built incrementally
        self.ph_refs = []      # weakref per output placeholder (liveness)
        self.results = None    # flat concrete outputs once forced

    def record(self, op, params, arrays):
        """Append one op call; returns placeholders shaped like fn's output
        (or raises, in which case nothing was appended — all segment state
        is committed atomically at the end)."""
        # dynamic scalar params become runtime operands here too: baking a
        # per-step lr into the segment key would recompile the segment
        # every step (the exact churn dynamic_params exists to prevent)
        dyn_keys, dyn_vals, params = op.split_dynamic(params)
        pkey = _ENV.param_key(op, params)
        descs, in_avals = [], []
        new_ext = []   # (id-or-None, value) staged; committed on success
        staged_pos = {}

        def ext_slot(val, ident):
            pos = self._ext_pos.get(ident) if ident is not None else None
            if pos is None and ident is not None:
                pos = staged_pos.get(ident)
            if pos is None:
                pos = len(self.ext) + len(new_ext)
                new_ext.append((ident, val))
                if ident is not None:
                    staged_pos[ident] = pos
            return pos

        for a in arrays:
            if type(a) is _Placeholder and a._seg is self \
                    and self.results is None:
                descs.append(("s", a._slot))
                in_avals.append((a._aval.shape, _dtype_str(a._aval.dtype)))
                continue
            if type(a) is _Placeholder:
                a = a._mxtpu_force()
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is None or dtype is None:
                raise TypeError(f"cannot bulk non-array input {type(a)}")
            aval = (tuple(shape), _dtype_str(dtype))
            descs.append(("e", ext_slot(a, id(a))) + aval)
            in_avals.append(aval)
        for v in dyn_vals:  # scalars: tiny, no dedup needed
            descs.append(("d", ext_slot(v, None)))
        is_tuple, out_avals = _infer_out(op, params, dyn_keys, dyn_vals,
                                         pkey, tuple(in_avals))
        # ---- commit (nothing above mutated segment state)
        for ident, val in new_ext:
            self.ext.append(val)
            if ident is not None:
                self._ext_pos[ident] = len(self.ext) - 1
        base = len(self.avals)
        self.avals.extend(out_avals)
        descs = tuple(descs)
        self.entries.append((op, params, dyn_keys, descs, base,
                             len(out_avals)))
        self.key_parts.append((pkey, dyn_keys, descs))
        _STATS["bulk_ops"] += 1
        phs = tuple(_Placeholder(self, base + i, av)
                    for i, av in enumerate(out_avals))
        self.ph_refs.extend(weakref.ref(p) for p in phs)
        return phs if is_tuple else phs[0]

    def force(self):
        """Compile (or fetch) and run the segment; returns flat results."""
        if self.results is None:
            self._flush()
        return self.results

    def _flush(self):
        import jax

        st = _state()
        if st.seg is self:
            st.seg = None  # close: later ops start a fresh segment
        n = len(self.entries)
        _STATS["bulk_segments"] += 1
        if n > _STATS["bulk_max_segment"]:
            _STATS["bulk_max_segment"] = n
        # dead-output elimination: outputs whose placeholder has already
        # been dropped (chained intermediates) can never be read — keeping
        # them as executable outputs would force XLA to materialize every
        # intermediate and defeat fusion across the segment
        live = tuple(i for i, r in enumerate(self.ph_refs)
                     if r() is not None)
        key = (self.device, live, tuple(self.key_parts))
        fn = _SEG_CACHE.get(key)
        if fn is None:
            _STATS["bulk_cache_miss"] += 1
            fn = jax.jit(_build_segment_fn(self.entries, len(self.avals),
                                           live))
            _SEG_CACHE[key] = fn
        else:
            _STATS["bulk_cache_hit"] += 1
        results = [None] * len(self.avals)
        try:
            outs = fn(*self.ext)
        except Exception:
            # semantics over speed: replay the recorded ops eagerly so the
            # cells still resolve even if segment compilation fails
            _STATS["bulk_fallback_eager"] += 1
            outs = _build_segment_fn(self.entries, len(self.avals),
                                     live)(*self.ext)
        for i, v in zip(live, outs):
            results[i] = v
        self.results = results
        # release the recording state: surviving placeholders only need
        # `results`; keeping `ext` would pin every external input buffer
        # (pre-update weights, grads) for the placeholders' lifetime
        self.entries = self.key_parts = self.ph_refs = ()
        self.ext = ()
        self._ext_pos = {}


def _build_segment_fn(entries, total, live):
    def seg_fn(*ext):
        flat = [None] * total
        for op, params, dyn_keys, descs, base, n in entries:
            ins, dynkw, di = [], {}, 0
            for d in descs:
                tag = d[0]
                if tag == "s":
                    ins.append(flat[d[1]])
                elif tag == "e":
                    ins.append(ext[d[1]])
                else:  # "d": dynamic scalar, by dyn_keys order
                    dynkw[dyn_keys[di]] = ext[d[1]]
                    di += 1
            fn = op.closed(params)
            r = fn(*ins, **dynkw) if dynkw else fn(*ins)
            rs = r if isinstance(r, tuple) else (r,)
            for i, v in enumerate(rs):
                flat[base + i] = v
        return tuple(flat[i] for i in live)

    return seg_fn


def _infer_out(op, params, dyn_keys, dyn_vals, pkey, in_avals):
    """FInferShape/Type for one bulked op: jax.eval_shape with a cache so
    steady-state recording never re-traces. Dynamic scalars are bound as
    constants for inference — output avals don't depend on their values."""
    k = (pkey, dyn_keys, in_avals)
    r = _AVAL_CACHE.get(k)
    if r is None:
        import jax

        structs = [jax.ShapeDtypeStruct(s, d) for s, d in in_avals]
        full = dict(params, **dict(zip(dyn_keys, dyn_vals))) \
            if dyn_keys else params
        out = jax.eval_shape(op.closed(full), *structs)
        is_tuple = isinstance(out, tuple)
        flat = list(out) if is_tuple else [out]
        r = (is_tuple, tuple(flat))
        _AVAL_CACHE[k] = r
    return r


class _Env:
    """Cross-module handles resolved once when bulking is first enabled."""

    param_key = None
    is_recording = None
    trace_active = None

    @classmethod
    def resolve(cls):
        from . import autograd
        from .jit import _active
        from .ops.registry import _param_key

        cls.param_key = staticmethod(_param_key)
        cls.is_recording = staticmethod(autograd.is_recording)
        cls.trace_active = staticmethod(_active)


_ENV = _Env


def _bulk_record(op, params, arrays, device):
    """Dispatch hook called from ops.registry on every eager op while
    bulking has ever been enabled. Returns NotImplemented to decline (the
    caller then dispatches eagerly)."""
    st = _state()
    if st.size <= 0:
        return NotImplemented
    if _ENV.is_recording() or _ENV.trace_active() is not None:
        return NotImplemented
    seg = st.seg
    if seg is None or seg.results is not None or seg.device is not device:
        if seg is not None and seg.results is None:
            seg.force()  # device switch: preserve program order
        seg = st.seg = _Segment(device)
    try:
        out = seg.record(op, params, arrays)
    except Exception:
        _STATS["bulk_fallback_eager"] += 1
        if not seg.entries:
            st.seg = None
        return NotImplemented
    if len(seg.entries) >= st.size:
        seg.force()
    return out


_HOOK_INSTALLED = False


def _install_hook():
    global _HOOK_INSTALLED
    if _HOOK_INSTALLED:
        return
    from .ops import registry

    _Env.resolve()
    registry._set_bulk_hook(_bulk_record, _Placeholder)
    _HOOK_INSTALLED = True


def set_bulk_size(size):
    """Set maximum number of ops to bulk per lazy segment (engine.py:26).
    Returns the previous value. 0 disables bulking (and forces any open
    segment so no lazy cells leak out of the bulked region)."""
    st = _state()
    prev, st.size = st.size, int(size)
    if st.size > 0:
        _install_hook()
    elif st.seg is not None and st.seg.results is None:
        st.seg.force()
    return prev


def flush():
    """Force the current thread's open segment, if any (used by
    mx.nd.waitall and the bulk scope exit)."""
    st = _state()
    if st.seg is not None and st.seg.results is None:
        st.seg.force()
    st.seg = None


@contextlib.contextmanager
def bulk(size):
    """Scope bulking (engine.py:45): ops inside accumulate into lazy
    segments of up to `size` ops. Exception-safe and nestable; the open
    segment is forced on exit either way."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        flush()
        set_bulk_size(prev)
