"""Engine control shim.

Parity: python/mxnet/engine.py (bulk/set_bulk_size over the dependency
engine, include/mxnet/engine.h:311). TPU-native: PJRT's async dispatch is the
dependency engine — ops return immediately and sequence on buffer futures —
and XLA fusion inside jitted executables is the op-bulking analogue. The
bulk-size knobs are therefore accepted for API compatibility and recorded,
but the actual batching decision belongs to jit tracing (mx.jit.trace /
hybridize), which compiles whole steps into one executable.
"""
from __future__ import annotations

import contextlib

__all__ = ["set_bulk_size", "bulk"]

_BULK_SIZE = 0


def set_bulk_size(size):
    """Set maximum number of ops to bulk (engine.py:26). Returns the
    previous value. On TPU this is advisory — jit tracing supersedes it."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Scope bulking hint (engine.py:45)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
