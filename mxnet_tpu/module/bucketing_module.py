"""BucketingModule — variable-length training over bucketed shapes.

Capability parity with python/mxnet/module/bucketing_module.py:40. The
reference binds one executor group per bucket against shared memory; here
each bucket is a Module whose shape-specialized XLA executables live in the
per-bucket executor cache (SURVEY.md §7 hard part 3: dynamic shapes →
shape-keyed executable caches), and parameters are kept coherent by syncing
the live values into a bucket's module on every switch — the optimizer
state lives in a single shared Updater keyed by parameter name, so momentum
etc. follow the parameters across buckets.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._compression_params = compression_params
        if work_load_list is not None or group2ctxs is not None:
            raise MXNetError(
                "work_load_list/group2ctxs are not supported: device "
                "placement on TPU is mesh sharding (mx.parallel), not "
                "per-executor workload splitting")
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self._params_dirty = False
        self._monitor = None

    # ------------------------------------------------------------- helpers
    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names=data_names,
                      label_names=label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        return self._curr_module.symbol

    # ---------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None, \
            "shared_module is not supported for BucketingModule"
        self.binded = True
        self.for_training = for_training
        self._grad_req = grad_req
        self._inputs_need_grad = inputs_need_grad
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False, grad_req=grad_req)
        self._buckets = {self._default_bucket_key: module}
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Make `bucket_key` current, binding its module on first use
        (bucketing_module.py:switch_bucket)."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self._inputs_need_grad, force_rebind=False,
                        grad_req=self._grad_req)
            if self.params_initialized:
                module.init_params(arg_params=self._arg_snapshot(),
                                   aux_params=self._aux_snapshot(),
                                   allow_missing=False, force_init=True)
            if getattr(self._curr_module, 'optimizer_initialized', False):
                module.borrow_optimizer(self._curr_module)
            self._buckets[bucket_key] = module
        if bucket_key != self._curr_bucket_key:
            # carry the live parameter values into the target bucket
            new_module = self._buckets[bucket_key]
            if self.params_initialized:
                arg, aux = self._curr_module.get_params()
                new_module.set_params(arg, aux, allow_missing=False,
                                      force_init=True)
            self._curr_module = new_module
            self._curr_bucket_key = bucket_key
            if self._monitor is not None:
                self._curr_module.install_monitor(self._monitor)

    def _arg_snapshot(self):
        return self._curr_module.get_params()[0]

    def _aux_snapshot(self):
        return self._curr_module.get_params()[1]

    # -------------------------------------------------------------- params
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self._curr_module.set_params(arg_params, aux_params,
                                     allow_missing=allow_missing,
                                     force_init=force_init,
                                     allow_extra=allow_extra)
        self.params_initialized = True

    # ----------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params,
                                         force_init=force_init)
        if self._compression_params and self._curr_module._kvstore:
            self._curr_module._kvstore.set_gradient_compression(
                self._compression_params)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    # ------------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        self._monitor = mon
        self._curr_module.install_monitor(mon)

    def save_optimizer_states(self, fname):
        self._curr_module.save_optimizer_states(fname)

    def load_optimizer_states(self, fname):
        self._curr_module.load_optimizer_states(fname)
