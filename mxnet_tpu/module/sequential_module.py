"""SequentialModule — chain Modules head-to-tail.

Capability parity with python/mxnet/module/sequential_module.py: each
child consumes the previous child's outputs as data; backward feeds input
gradients upstream. Used to compose a symbolic body with e.g. a
PythonLossModule head.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from .base_module import BaseModule


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names

    @property
    def output_names(self):
        return self._modules[-1].output_names

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def get_params(self):
        arg, aux = {}, {}
        for m in self._modules:
            a, x = m.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        for m in self._modules:
            m.init_params(initializer=initializer, arg_params=arg_params,
                          aux_params=aux_params, allow_missing=True,
                          force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        if not self._modules:
            raise MXNetError("SequentialModule is empty; call add() first")
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (m, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            m.bind(cur_shapes,
                   label_shapes if take_labels else None,
                   for_training=for_training,
                   inputs_need_grad=inputs_need_grad or i > 0,
                   force_rebind=force_rebind, grad_req=grad_req)
            # next module consumes this one's outputs, renamed to its
            # data_names (META_AUTO_WIRING semantics)
            if i + 1 < len(self._modules):
                nxt = self._modules[i + 1]
                cur_shapes = [
                    DataDesc(name, shape) for name, (_, shape) in
                    zip(nxt.data_names, m.output_shapes)]
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for m in self._modules:
            m.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                             optimizer_params=optimizer_params,
                             force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        batch = data_batch
        for i, (m, meta) in enumerate(zip(self._modules, self._metas)):
            m.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            take_labels = self._metas[i + 1].get(self.META_TAKE_LABELS, False)
            batch = DataBatch(
                data=m.get_outputs(),
                label=data_batch.label if take_labels else None,
                pad=data_batch.pad,
                provide_data=[DataDesc(n, o.shape) for n, o in zip(
                    self._modules[i + 1].data_names, m.get_outputs())],
                provide_label=(data_batch.provide_label
                               if take_labels else None))

    def backward(self, out_grads=None):
        grads = out_grads
        for i in range(len(self._modules) - 1, -1, -1):
            m = self._modules[i]
            m.backward(out_grads=grads)
            if i > 0:  # the bottom module's input grads are never consumed
                grads = m.get_input_grads()

    def update(self):
        for m in self._modules:
            m.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for m, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                m.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        for m in self._modules:
            m.install_monitor(mon)
