"""PythonModule / PythonLossModule — write a Module in plain Python.

Capability parity with python/mxnet/module/python_module.py: a base class
wiring the BaseModule lifecycle for computation expressed directly in
Python/numpy (no Symbol), plus the loss-module specialization whose
backward produces the input gradient fed to a preceding module (used with
SequentialModule, e.g. custom loss heads).
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd  # op-wrapper package (softmax, one_hot, ...)
from .base_module import BaseModule


class PythonModule(BaseModule):
    """Subclass and override forward/backward (python_module.py:35)."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else None
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError

    def update(self):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_shapes is not None:
            eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """A pluggable loss head (python_module.py:PythonLossModule): forward
    stores scores, backward emits d(loss)/d(scores) via `grad_func` or the
    built-in logistic gradient."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, (name + "_output",),
                         logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "loss module is the graph head"
        if self._grad_func is not None:
            g = self._grad_func(self._scores, self._labels)
            if not isinstance(g, nd.NDArray):
                g = nd.array(np.asarray(g))
            self._scores_grad = g
        else:  # d/dx of softmax-CE with one-hot labels ≈ (p - y)
            p = nd.softmax(self._scores)
            y = nd.one_hot(self._labels, p.shape[-1])
            self._scores_grad = p - y

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]

    def install_monitor(self, mon):
        pass
