"""Module — symbolic training over one or more device contexts.

Parity: python/mxnet/module/module.py + executor_group.py. Multi-context
data parallelism slices the batch across executors like
DataParallelExecutorGroup (executor_group.py:144,282); on TPU the preferred
scale-out is the mesh path (parallel/), but the multi-ctx API is kept so
reference scripts run unchanged.
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import InitDesc, Uniform
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint)
from ..ndarray import ndarray as nd
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .. import optimizer as opt
from .base_module import BaseModule, _as_list

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        context = context or current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._state_names = list(state_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._execs = []
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = None
        self._slices = None

    # ------------------------------------------------------------ factories
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        from ..model import save_checkpoint

        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params())
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # ------------------------------------------------------------ properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        """Full-batch output shapes, inferred from the bound input shapes
        (NOT from per-device executors, whose batch dim is the per-context
        slice — the reference reports the concatenated shape)."""
        assert self.binded
        shapes = {}
        for desc in list(self._data_shapes) + list(self._label_shapes or []):
            name = desc[0] if isinstance(desc, (tuple, list)) else desc.name
            shape = (tuple(desc[1]) if isinstance(desc, (tuple, list))
                     else tuple(desc.shape))
            shapes[name] = shape
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self._output_names, out_shapes))

    # ----------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._execs = []
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self._data_shapes = [x if isinstance(x, tuple) else tuple(x)[:2] and x
                             for x in data_shapes]
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else []
        ndev = len(self._context)
        batch_axis_sizes = {}
        # slice batch across contexts (decide_slices, executor_group.py:282)
        self._slices = []
        total_batch = self._data_shapes[0][1][0] if not hasattr(self._data_shapes[0], "shape") else self._data_shapes[0].shape[0]

        def _shape_of(desc):
            return tuple(desc[1]) if isinstance(desc, (tuple, list)) else tuple(desc.shape)

        def _name_of(desc):
            return desc[0] if isinstance(desc, (tuple, list)) else desc.name

        total_batch = _shape_of(self._data_shapes[0])[0]
        if total_batch % ndev != 0:
            raise MXNetError(f"batch size {total_batch} not divisible by "
                             f"number of contexts {ndev}")
        step = total_batch // ndev
        self._slices = [slice(i * step, (i + 1) * step) for i in range(ndev)]
        req = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names or name in self._label_names:
                req[name] = "null"
            elif name in self._fixed_param_names:
                req[name] = "null"
            else:
                req[name] = grad_req if for_training else "null"
        if inputs_need_grad:
            for name in self._data_names:
                req[name] = "write"
        shapes = {}
        for desc in self._data_shapes:
            s = _shape_of(desc)
            shapes[_name_of(desc)] = (step,) + s[1:]
        for desc in self._label_shapes:
            s = _shape_of(desc)
            shapes[_name_of(desc)] = (step,) + s[1:]
        self._execs = [
            self._symbol.simple_bind(ctx, grad_req=req, **shapes)
            for ctx in self._context]
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    # --------------------------------------------------------------- params
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        initializer = initializer if initializer is not None else Uniform(0.01)
        ex0 = self._execs[0]
        if self._arg_params is None:
            self._arg_params = {n: nd_zeros(ex0.arg_dict[n].shape, cpu(),
                                            ex0.arg_dict[n].dtype)
                                for n in self._param_names}
        if self._aux_params is None:
            self._aux_params = {n: nd_zeros(ex0.aux_dict[n].shape, cpu(),
                                            ex0.aux_dict[n].dtype)
                                for n in self._aux_names}

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr._set_data(cache_arr._data)
            else:
                if not allow_missing and initializer is None:
                    raise MXNetError(f"{name} is not presented")
                if initializer is not None:
                    initializer(InitDesc(name), arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            desc = InitDesc(name)
            if aux_params is not None and name in aux_params:
                arr._set_data(aux_params[name]._data)
            elif initializer is not None and not name.endswith("rng_key"):
                initializer(desc, arr)
        self.params_initialized = True
        self._params_dirty = False
        for ex in self._execs:
            ex.copy_params_from(self._arg_params, self._aux_params,
                                allow_extra_params=True)

    def get_params(self):
        assert self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return self._arg_params, self._aux_params

    def _sync_params_from_devices(self):
        if not self._execs:
            return
        ex0 = self._execs[0]
        for n in self._param_names:
            self._arg_params[n]._set_data(ex0.arg_dict[n]._data)
        for n in self._aux_names:
            self._aux_params[n]._set_data(ex0.aux_dict[n]._data)
        self._params_dirty = False

    # ------------------------------------------------------------ optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = sum(
            (s.stop - s.start) for s in self._slices)
        rescale_grad = 1.0 / batch_size
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
            # per-parameter multipliers from symbol attrs (AttrScope /
            # Variable(lr_mult=...); reference model.py attr_dict flow)
            attrs = self.symbol.attr_dict()
            lr_mult = {n: float(a["__lr_mult__"])
                       for n, a in attrs.items() if "__lr_mult__" in a}
            wd_mult = {n: float(a["__wd_mult__"])
                       for n, a in attrs.items() if "__wd_mult__" in a}
            if lr_mult:
                optimizer.set_lr_mult(lr_mult)
            if wd_mult:
                optimizer.set_wd_mult(wd_mult)
        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kvstore:
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            _initialize_kvstore(
                kvstore=kvstore,
                param_arrays=[[ex.arg_dict[n] for ex in self._execs]
                              for n in self._param_names],
                arg_params=self._arg_params,
                param_names=self._param_names,
                update_on_kvstore=update_on_kvstore)
        if not update_on_kvstore:
            self._updater = opt.get_updater(self._optimizer)
        self.optimizer_initialized = True


    # ------------------------------------------------------------ execution
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        data = data_batch.data
        labels = data_batch.label or []
        for i, ex in enumerate(self._execs):
            sl = self._slices[i]
            feeds = {}
            for name, arr in zip(self._data_names, data):
                feeds[name] = arr[sl] if len(self._execs) > 1 else arr
            for name, arr in zip(self._label_names, labels):
                feeds[name] = arr[sl] if len(self._execs) > 1 else arr
            ex.forward(is_train=is_train, **{
                k: (v if isinstance(v, NDArray) else nd.array(v))
                for k, v in feeds.items()})

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for ex in self._execs:
            ex.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(
                [[ex.arg_dict[n] for ex in self._execs]
                 for n in self._param_names],
                [[ex.grad_dict.get(n) for ex in self._execs]
                 for n in self._param_names],
                self._kvstore, self._param_names)
        else:
            _update_params(
                [[ex.arg_dict[n] for ex in self._execs]
                 for n in self._param_names],
                [[ex.grad_dict.get(n) for ex in self._execs]
                 for n in self._param_names],
                updater=self._updater,
                num_device=len(self._context),
                kvstore=self._kvstore,
                param_names=self._param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        outs = [ex.outputs for ex in self._execs]
        if merge_multi_context and len(outs) > 1:
            return [nd.concatenate([o[i] for o in outs], axis=0)
                    for i in range(len(outs[0]))]
        return outs[0] if merge_multi_context else outs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        grads = [[ex.grad_dict.get(n) for n in self._data_names]
                 for ex in self._execs]
        if merge_multi_context and len(grads) > 1:
            return [nd.concatenate([g[i] for g in grads], axis=0)
                    for i in range(len(grads[0]))]
        return grads[0] if merge_multi_context else grads

    def get_states(self, merge_multi_context=True):
        return []

    def set_states(self, states=None, value=None):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels if not pre_sliced else labels[0])),
            dict(zip(self._output_names, self.get_outputs())))

    def install_monitor(self, mon):
        for ex in self._execs:
            mon.install(ex)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else []
        self.binded = False
        execs_params = (self._arg_params, self._aux_params)
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        for ex in self._execs:
            ex.copy_params_from(*execs_params, allow_extra_params=True)

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True
