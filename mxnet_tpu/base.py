"""Base utilities: errors, dtype maps, registries.

TPU-native re-design of the reference's dmlc-core surface
(/root/reference/include/mxnet/base.h, 3rdparty dmlc-core usage sites):
typed parameter structs become plain keyword arguments validated at the
registry layer, logging/CHECK become Python exceptions, and `dmlc::GetEnv`
becomes :func:`getenv`.
"""
from __future__ import annotations

import os

import numpy as _np

__all__ = ["MXNetError", "getenv", "string_types", "numeric_types", "integer_types"]

MXNET_TPU_MAJOR = 2
MXNET_TPU_MINOR = 0
__version__ = "2.0.0.tpu0"


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: dmlc::Error / MXGetLastError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype name <-> numpy dtype tables (reference: include/mxnet/base.h TypeFlag).
_DTYPE_NAMES = [
    "float32", "float64", "float16", "uint8", "int32", "int8", "int64",
    "bool", "int16", "uint16", "uint32", "uint64", "bfloat16",
]
DTYPE_NAME_TO_NP = {n: _np.dtype(n) if n != "bfloat16" else None for n in _DTYPE_NAMES}


def np_dtype(dtype):
    """Canonicalize a dtype-ish value to something jax/numpy accepts."""
    if dtype is None:
        return None
    if isinstance(dtype, str) and dtype == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return _np.dtype(dtype) if not hasattr(dtype, "dtype") else dtype


def getenv(name, default):
    """Typed env lookup (parity: dmlc::GetEnv, env list in
    docs/static_site/src/pages/api/faq/env_var.md)."""
    val = os.environ.get(name)
    if val is None:
        return default
    if isinstance(default, bool):
        return val not in ("0", "false", "False", "")
    return type(default)(val)


class _Registry:
    """Minimal name->object registry with alias support."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}

    def register(self, obj, name=None):
        key = (name or getattr(obj, "__name__", None) or str(obj)).lower()
        self._map[key] = obj
        return obj

    def get(self, name):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(self._map)}"
            )
        return self._map[key]

    def find(self, name):
        return self._map.get(name.lower())

    def keys(self):
        return sorted(self._map)


def listify(x):
    """Normalize control-flow data/state arguments: None -> ([], False),
    list/tuple -> (list, True), scalar -> ([x], False). Shared by the
    eager (ndarray/contrib.py) and symbolic (symbol/contrib.py) control
    flow so the nesting contract cannot drift."""
    if x is None:
        return [], False
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False
