"""Sharded streaming RecordIO ingestion with device-prefetch overlap
and deterministic mid-epoch resume (docs/data.md).

The third leg of the train-at-scale story after whole-program capture
(mxnet_tpu.capture) and elastic recovery (resilience): with the captured
step at the HBM roofline, the stall source at dp>=8 is the input
pipeline — exactly the regime the MXNet paper's RecordIO/threaded-
iterator IO design and the TensorFlow paper's overlapped input pipelines
were built for (PAPERS.md). Three layers:

- :class:`RecordStream` — index-based **range reads** over one or many
  ``.rec`` shards (each with the sibling ``.idx`` offset index
  ``tools/im2rec.py`` emits; no full-file scan), an **epoch-seeded
  shard-and-chunk shuffle** identical on every rank, and a **strided
  rank partition**: order position ``p`` belongs to rank
  ``p % num_parts``, so every sample lands on exactly one of the
  ``num_parts`` host/dp ranks per epoch — uneven tail included. Each
  record read is CRC-verified against the index
  (``recordio.read_record_at``); a corrupt record raises a structured
  ``RecordCorruptError`` or, under ``MXNET_TPU_DATA_CORRUPT_POLICY=
  skip``, is counted (``io_records_corrupt``) and skipped.
- :class:`StreamBatchIter` — lockstep batch assembly on a decode thread
  pool. Every rank produces the SAME number of batches per epoch
  (``((N - cursor) // num_parts) // batch_size``; the global tail that
  cannot fill one whole lockstep batch rolls off at the epoch edge, as
  in any dp training loop), and every produced batch carries its own
  **resume token** (:class:`StreamBatch` ``.state``): restoring any
  token re-produces the exact remaining batch stream, bitwise — across
  kill-resume at the same ``num_parts`` AND across a mesh-shrink
  re-partition onto fewer ranks (the token records the shared global
  cursor; new ranks re-stride the remaining order positions).
- :class:`DevicePrefetcher` — per-host double-buffered device prefetch:
  a daemon worker ``jax.device_put``\\ s the next K batches (sharded
  along the dp axis via the mesh's NamedSharding, non-blocking) while
  the current captured step executes, so host decode, H2D transfer, and
  device compute overlap. The consumer pops an already-device-resident
  batch — ``step.data_wait`` collapses to the queue sync — and the
  prefetcher's resume token is always the LAST BATCH HANDED TO THE
  CONSUMER: ring contents that were prefetched but never consumed are
  discarded on restore and regenerate from the source, never replayed.

Resume tokens serialize into the CheckpointManager v2 manifest
(``save(..., data_iter=...)`` / ``restore_latest(..., data_iter=...)``,
docs/resilience.md) so elastic recovery and mesh-shrink replay never see
a sample twice. ``tools/stream_bench.py`` (also ``bench.py
--data=stream``) gates the overlap: ``mxnet_tpu_input_stall_fraction``
<= 0.05 at dp=8 with prefetch on.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref

import numpy as _np

from ..base import MXNetError
from ..observability import trace as _obs_trace
from .. import recordio as _recordio

__all__ = ["RecordStream", "StreamBatchIter", "StreamBatch",
           "DevicePrefetcher", "raw_decoder", "image_decoder",
           "token_decoder", "resolve_bucket_edges", "live_positions",
           "stats", "reset_stats", "STATE_VERSION"]

# docs/observability.md "streaming ingestion" counters; merged into
# profiler.dispatch_stats() like every subsystem's _STATS.
_STATS = {
    "io_batches_streamed": 0,   # host batches assembled by StreamBatchIter
    "io_records_corrupt": 0,    # CRC-failed records skipped (policy=skip)
    "io_prefetch_depth": 0,     # DevicePrefetcher ring occupancy (last seen)
    "io_stream_resumes": 0,     # iterators restored from a resume token
    "io_bucket_batches": 0,     # batches padded to a token-length bucket
    "io_bucket_pad_rows": 0,    # rows that needed padding to their bucket
}

STATE_VERSION = 1

# live batch iterators, so the input_stall_high alert rule can name the
# streaming iterator position in its evidence (observability/alerts.py)
_LIVE_LOCK = threading.Lock()
_LIVE = weakref.WeakSet()


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def live_positions():
    """Positions of every live :class:`StreamBatchIter` — the evidence
    the ``input_stall_high`` alert attaches so an incident names WHERE
    in the epoch the input-bound loop was starving."""
    with _LIVE_LOCK:
        iters = list(_LIVE)
    out = []
    for it in iters:
        try:
            out.append(it.position())
        except Exception:
            continue
    return out


def _corrupt_policy(override=None):
    policy = (override if override is not None else
              os.environ.get("MXNET_TPU_DATA_CORRUPT_POLICY", "raise"))
    policy = str(policy).strip().lower()
    if policy not in ("raise", "skip"):
        raise ValueError(
            f"corrupt-record policy must be 'raise' or 'skip', got "
            f"{policy!r} (MXNET_TPU_DATA_CORRUPT_POLICY)")
    return policy


def resolve_bucket_edges(override=None):
    """Token-length bucket boundaries: an explicit iterable of ints, or
    the ``MXNET_TPU_DATA_BUCKET_EDGES`` env knob ('32,64,128'); None/''
    disables bucketing. Returned sorted ascending and de-duplicated —
    the FIXED set of sequence shapes every padded batch snaps to, so a
    captured step compiles at most ``len(edges)`` signatures no matter
    how batch membership shifts (docs/data.md)."""
    if override is not None:
        raw = list(override)
    else:
        env = os.environ.get("MXNET_TPU_DATA_BUCKET_EDGES", "").strip()
        if not env:
            return None
        raw = [p for p in env.split(",") if p.strip()]
    try:
        edges = sorted({int(e) for e in raw})
    except (TypeError, ValueError):
        raise ValueError(
            f"bucket edges must be integers, got {raw!r} "
            "(MXNET_TPU_DATA_BUCKET_EDGES)")
    if not edges:
        return None
    if edges[0] < 1:
        raise ValueError(
            f"bucket edges must be positive, got {edges} "
            "(MXNET_TPU_DATA_BUCKET_EDGES)")
    return tuple(edges)


# ------------------------------------------------------------------ decoders

def raw_decoder(data_shape, label_width=1, cost_s=0.0):
    """Decoder for records whose payload is raw little-endian float32
    bytes of ``data_shape`` — the synthetic-decode form the tests and
    ``tools/stream_bench.py`` pack. ``cost_s`` sleeps per record to
    emulate a real decoder's latency for overlap benchmarking (sleep,
    not spin, so the emulated cost never steals CPU from the step)."""
    shape = tuple(int(d) for d in data_shape)
    n = 1
    for d in shape:
        n *= d

    def decode(header, payload):
        if cost_s > 0:
            time.sleep(cost_s)
        arr = _np.frombuffer(payload, dtype=_np.float32, count=n)
        arr = arr.reshape(shape)
        lab = _np.atleast_1d(_np.asarray(header.label, _np.float32)).ravel()
        label = _np.zeros(label_width, _np.float32)
        label[:min(label_width, lab.size)] = lab[:label_width]
        return arr, label

    return decode


def token_decoder(lm_shift=True, dtype=_np.float32):
    """Decoder for variable-length text records: the payload is raw
    little-endian int32 token ids (any count — this is the decoder the
    token-length buckets exist for). With ``lm_shift`` (default) each
    record yields the next-token LM pair ``(tokens[:-1], tokens[1:])``
    — both length T-1, padded together to the bucket edge; otherwise
    the full sequence with the record header's label."""

    def decode(header, payload):
        toks = _np.frombuffer(payload, dtype=_np.int32).astype(dtype)
        if lm_shift:
            if toks.size < 2:
                raise ValueError(
                    f"LM records need >= 2 tokens, got {toks.size}")
            return toks[:-1], toks[1:]
        lab = _np.atleast_1d(_np.asarray(header.label, _np.float32))
        return toks, lab.ravel()[:1]

    return decode


def image_decoder(data_shape, resize=0, mean=None, std=None):
    """Deterministic (augmentation-free) image decoder: PIL decode,
    shorter-side resize, center crop to ``(C, H, W)``, float32 NCHW with
    optional per-channel mean/std normalization. Training-time random
    augmentation stays with ``io.ImageRecordIter``; streaming resume is
    bitwise only because this decode has no RNG."""
    channels, height, width = (int(d) for d in data_shape)
    mean_a = _np.asarray(mean if mean is not None else [0.0] * channels,
                         _np.float32)
    std_a = _np.asarray(std if std is not None else [1.0] * channels,
                        _np.float32)

    def decode(header, payload):
        from io import BytesIO

        from PIL import Image

        img = Image.open(BytesIO(payload))
        img = img.convert("L" if channels == 1 else "RGB")
        if resize > 0:
            scale = resize / min(img.size)
            img = img.resize((max(width, round(img.size[0] * scale)),
                              max(height, round(img.size[1] * scale))))
        if img.size != (width, height):
            if img.size[0] < width or img.size[1] < height:
                img = img.resize((width, height))
            else:
                x = (img.size[0] - width) // 2
                y = (img.size[1] - height) // 2
                img = img.crop((x, y, x + width, y + height))
        arr = _np.asarray(img, dtype=_np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = (arr - mean_a) / std_a
        lab = _np.atleast_1d(_np.asarray(header.label, _np.float32)).ravel()
        return arr.transpose(2, 0, 1), lab[:1]

    return decode


# -------------------------------------------------------------- RecordStream

class _Shard:
    """One ``.rec`` file plus its loaded ``.idx`` offset index."""

    __slots__ = ("rec_path", "idx_path", "entries", "name")

    def __init__(self, rec_path, idx_path=None):
        self.rec_path = os.fspath(rec_path)
        base = (self.rec_path[:-4] if self.rec_path.endswith(".rec")
                else self.rec_path)
        self.idx_path = os.fspath(idx_path) if idx_path else base + ".idx"
        if not os.path.isfile(self.idx_path):
            raise MXNetError(
                f"streaming reads need an offset index: {self.idx_path} is "
                "missing (tools/im2rec.py writes one next to every .rec)")
        self.entries = _recordio.load_index(self.idx_path)
        if not self.entries:
            raise MXNetError(f"offset index {self.idx_path} is empty")
        # the index must reach EOF: an index from an earlier, shorter
        # pack of the same data has only valid offsets — trusting it
        # would silently train on a prefix of the dataset
        size = os.path.getsize(self.rec_path)
        last = self.entries[-1]
        ok = 0 <= last.offset < size
        if ok:
            try:
                with open(self.rec_path, "rb") as f:
                    f.seek(last.offset)
                    ok = (_recordio.read_logical_record(f) is not None
                          and f.tell() == size)
            except (OSError, ValueError):
                ok = False
        if not ok:
            raise MXNetError(
                f"offset index {self.idx_path} is stale for "
                f"{self.rec_path}: its last entry does not frame the "
                "file's final record (rebuild with tools/im2rec.py)")
        self.name = os.path.basename(self.rec_path)


class RecordStream:
    """Deterministic sharded streaming reader over indexed RecordIO.

    Parameters
    ----------
    paths : str | [str] — one or many ``.rec`` shards; each needs the
        sibling ``.idx`` index. Shards are ordered by sorted path so
        every rank agrees on the global record numbering.
    part_index, num_parts : this rank's slice. The partition is strided
        over epoch-order POSITIONS (position ``p`` belongs to rank
        ``p % num_parts``), so the union over ranks covers every record
        exactly once per epoch, uneven tail included — and a resume
        token's global cursor re-partitions cleanly onto a different
        ``num_parts`` after a mesh shrink.
    shuffle, seed : epoch-seeded shard-and-chunk shuffle — the chunk
        order across all shards and the record order within each chunk
        are permuted by an RNG seeded from ``(seed, epoch)``, identical
        on every rank, while reads stay range-local.
    chunk_records : shuffle granularity (``MXNET_TPU_DATA_CHUNK_RECORDS``,
        default 64 records per chunk).
    corrupt_policy : ``raise`` | ``skip``
        (``MXNET_TPU_DATA_CORRUPT_POLICY``).
    """

    def __init__(self, paths, part_index=0, num_parts=1, shuffle=False,
                 seed=0, chunk_records=None, corrupt_policy=None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self.shards = [_Shard(p) for p in
                       sorted(os.fspath(p) for p in paths)]
        num_parts = int(num_parts)
        part_index = int(part_index)
        if num_parts < 1 or not 0 <= part_index < num_parts:
            raise ValueError(
                f"need 0 <= part_index < num_parts, got {part_index}/"
                f"{num_parts}")
        self.part_index = part_index
        self.num_parts = num_parts
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        if chunk_records is None:
            chunk_records = int(os.environ.get(
                "MXNET_TPU_DATA_CHUNK_RECORDS", "64"))
        self.chunk_records = max(1, int(chunk_records))
        self._policy = _corrupt_policy(corrupt_policy)
        self._shard_base = []
        self._chunks = []       # [(start_gid, stop_gid)] within one shard
        base = 0
        for shard in self.shards:
            self._shard_base.append(base)
            n = len(shard.entries)
            for lo in range(0, n, self.chunk_records):
                self._chunks.append((base + lo,
                                     base + min(lo + self.chunk_records, n)))
            base += n
        self.num_records = base
        self._tls = threading.local()

    def identity(self):
        """What a resume token must match: the dataset, not the rank."""
        return {"shards": [s.name for s in self.shards],
                "num_records": int(self.num_records)}

    def epoch_order(self, epoch):
        """Global record order (array of record ids) for one epoch —
        identical on every rank. Shuffle permutes whole chunks across
        shards, then records within each chunk, so range reads stay
        local while the sample order decorrelates across epochs."""
        if not self.shuffle:
            return _np.arange(self.num_records, dtype=_np.int64)
        rs = _np.random.RandomState(
            (self.seed * 2654435761 + (int(epoch) + 1) * 40503)
            & 0xFFFFFFFF)
        chunks = list(self._chunks)
        rs.shuffle(chunks)
        out = _np.empty(self.num_records, _np.int64)
        pos = 0
        for lo, hi in chunks:
            ids = _np.arange(lo, hi, dtype=_np.int64)
            rs.shuffle(ids)
            out[pos:pos + len(ids)] = ids
            pos += len(ids)
        return out

    def locate(self, gid):
        """Global record id -> (shard, IndexEntry)."""
        gid = int(gid)
        lo, hi = 0, len(self.shards) - 1
        while lo < hi:  # rightmost shard whose base <= gid
            mid = (lo + hi + 1) // 2
            if self._shard_base[mid] <= gid:
                lo = mid
            else:
                hi = mid - 1
        shard = self.shards[lo]
        return shard, shard.entries[gid - self._shard_base[lo]]

    def _file(self, shard):
        # one handle per (thread, shard): seek/read pairs must not
        # interleave across the decode pool's threads
        files = getattr(self._tls, "files", None)
        if files is None:
            files = self._tls.files = {}
        f = files.get(shard.rec_path)
        if f is None:
            f = files[shard.rec_path] = open(shard.rec_path, "rb")
        return f

    def close(self):
        """Close the CALLING thread's shard file handles. Handles opened
        by decode-pool threads are per-thread-local and close with their
        thread (StreamBatchIter.close shuts the pool down first)."""
        files = getattr(self._tls, "files", None)
        if files:
            for f in files.values():
                try:
                    f.close()
                except OSError:
                    pass
            files.clear()

    def read(self, gid):
        """Verified range-read of one record; returns the payload bytes,
        or None when the record failed verification and the policy is
        ``skip`` (counted in ``io_records_corrupt``)."""
        shard, entry = self.locate(gid)
        try:
            return _recordio.read_record_at(self._file(shard), entry,
                                            path=shard.rec_path)
        except _recordio.RecordCorruptError:
            if self._policy == "raise":
                raise
            _STATS["io_records_corrupt"] += 1
            return None

    def iter_records(self, epoch=0, start=0):
        """Yield ``(position, record_id, payload)`` for THIS rank's slice
        of the epoch: order positions ``p >= start`` with
        ``(p - start) % num_parts == part_index``. Corrupt records under
        policy ``skip`` are omitted (still counted); the partition
        itself covers every record exactly once across ranks."""
        order = self.epoch_order(epoch)
        p = int(start) + self.part_index
        while p < self.num_records:
            gid = int(order[p])
            payload = self.read(gid)
            if payload is not None:
                yield p, gid, payload
            p += self.num_parts


# ------------------------------------------------------------ batch assembly

class StreamBatch:
    """One assembled host batch plus the resume token that re-produces
    every batch AFTER it (``state`` — feed it to
    ``StreamBatchIter.restore`` / ``CheckpointManager.save(data_iter=)``).

    ``length`` is None except on token-length-bucketed text batches
    (``bucket_edges`` / ``MXNET_TPU_DATA_BUCKET_EDGES``), where it is
    the (batch,) int32 vector of REAL per-row sequence lengths — the
    mask consumers apply over the pad positions ``data``/``label`` were
    padded to (the bucket edge)."""

    __slots__ = ("data", "label", "state", "length")

    def __init__(self, data, label, state, length=None):
        self.data = data
        self.label = label
        self.state = state
        self.length = length

    def __iter__(self):  # (x, y) unpacking convenience
        return iter((self.data, self.label))


class StreamBatchIter:
    """Lockstep streaming batch iterator with deterministic resume.

    Single consumer (the training loop or a :class:`DevicePrefetcher`
    worker — never both). Every rank running the same configuration
    produces the same number of batches per epoch, and every yielded
    :class:`StreamBatch` carries the resume token of the stream AFTER
    that batch. ``epochs=None`` streams forever (epoch-seeded reshuffle
    at every epoch edge); ``epochs=N`` raises StopIteration after N
    full epochs.

    A corrupt record under policy ``skip`` keeps the batch geometry
    intact: its row is substituted with the batch's first valid row
    (counted in ``io_records_corrupt``), so the position arithmetic —
    and therefore bitwise resume and cross-rank lockstep — never shifts.
    """

    def __init__(self, source, batch_size, decode, part_index=0,
                 num_parts=1, shuffle=False, seed=0, chunk_records=None,
                 corrupt_policy=None, epochs=None, decode_threads=None,
                 batch_cost_s=0.0, bucket_edges=None, bucket_pad=0):
        from concurrent.futures import ThreadPoolExecutor

        if isinstance(source, RecordStream):
            conflicting = [name for name, passed in
                           (("part_index", part_index != 0),
                            ("num_parts", num_parts != 1),
                            ("shuffle", shuffle is not False),
                            ("seed", seed != 0),
                            ("chunk_records", chunk_records is not None),
                            ("corrupt_policy", corrupt_policy is not None))
                           if passed]
            if conflicting:
                raise ValueError(
                    "source is already a RecordStream: its own settings "
                    "govern the order/partition, and the conflicting "
                    f"argument(s) {conflicting} would be silently "
                    "ignored — configure them on the RecordStream")
            self.stream = source
        else:
            self.stream = RecordStream(
                source, part_index=part_index, num_parts=num_parts,
                shuffle=shuffle, seed=seed, chunk_records=chunk_records,
                corrupt_policy=corrupt_policy)
        self.batch_size = int(batch_size)
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.decode = decode
        self._epochs = None if epochs is None else int(epochs)
        # synthetic per-BATCH decode latency (sleep) for overlap
        # benchmarking (tools/stream_bench.py): one sleep per batch, not
        # per record — on a CPU-starved host every timer wakeup costs a
        # scheduler quantum, so a per-record decoder sleep would serialize
        # with compute instead of overlapping it
        self._batch_cost_s = float(batch_cost_s)
        if decode_threads is None:
            decode_threads = int(os.environ.get(
                "MXNET_TPU_DATA_DECODE_THREADS", "4"))
        self._pool_workers = max(1, int(decode_threads))
        self._pool = ThreadPoolExecutor(
            max_workers=self._pool_workers,
            thread_name_prefix="mxnet-tpu-data-decode")
        # token-length bucketing (variable-length text rows): pad every
        # batch's sequence dim up to the smallest edge that fits it, so
        # decoded lengths never leak into batch shapes — a captured step
        # compiles at most len(edges) signatures. Deliberately NOT part
        # of the resume token (like the decode fn, bucketing is
        # configuration the resuming iterator must be rebuilt with; the
        # token's order arithmetic is untouched by padding).
        self._bucket_edges = resolve_bucket_edges(bucket_edges)
        self._bucket_pad = bucket_pad
        self._epoch = 0
        self._cursor = 0        # within-epoch global position cursor
        self._epochs_done = 0
        self._order = None
        self._closed = False
        if self.batches_per_epoch == 0:
            raise MXNetError(
                f"{self.stream.num_records} records cannot fill one "
                f"lockstep batch of {self.batch_size} rows per rank over "
                f"{self.stream.num_parts} rank(s)")
        with _LIVE_LOCK:
            _LIVE.add(self)

    @classmethod
    def for_pod(cls, topology, source, batch_size, decode, **kw):
        """Per-host partition of the stream for a pod run: host ``h`` of
        a :class:`~mxnet_tpu.parallel.mesh.PodTopology` reads records
        ``gid % num_hosts == h`` (the PR-13 strided partition, so a
        host-count change after elastic shrink re-strides the SAME
        remainder instead of re-reading consumed records). Pass the
        result to :meth:`DevicePrefetcher.for_trainer` to overlap the
        host's decode with its devices' compute."""
        for name in ("part_index", "num_parts"):
            if name in kw:
                raise ValueError(
                    f"for_pod derives {name} from the topology "
                    f"(num_hosts={int(topology.num_hosts)}, "
                    f"this_host={int(topology.this_host)}); don't pass it")
        return cls(source, batch_size, decode,
                   part_index=int(topology.this_host),
                   num_parts=int(topology.num_hosts), **kw)

    # ------------------------------------------------------------ geometry

    @property
    def batches_per_epoch(self):
        """Lockstep batches per FULL epoch (cursor 0) — identical on
        every rank by construction."""
        return ((self.stream.num_records // self.stream.num_parts)
                // self.batch_size)

    def _batches_left(self):
        avail = self.stream.num_records - self._cursor
        return max(0, (avail // self.stream.num_parts) // self.batch_size)

    @property
    def epoch(self):
        return self._epoch

    # ----------------------------------------------------------- iteration

    def __iter__(self):
        return self

    def close(self):
        """Release the decode pool's threads and this thread's shard
        file handles (pool threads' per-thread handles close with their
        threads). Without an explicit close these are reclaimed only by
        GC — a job building one iterator per evaluation pass would
        accumulate threads and fds until then."""
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)
        self.stream.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __next__(self):
        if self._closed:
            raise RuntimeError("StreamBatchIter is closed")
        if self._batches_left() == 0:
            self._epochs_done += 1
            if self._epochs is not None \
                    and self._epochs_done >= self._epochs:
                raise StopIteration
            self._epoch += 1
            self._cursor = 0
            self._order = None
        with _obs_trace.span("data.fetch", epoch=self._epoch,
                             cursor=self._cursor):
            batch = self._assemble()
        _STATS["io_batches_streamed"] += 1
        return batch

    def _assemble(self):
        stream = self.stream
        if self._order is None:
            self._order = stream.epoch_order(self._epoch)
        base, bs, P = self._cursor, self.batch_size, stream.num_parts
        gids = [int(self._order[base + stream.part_index + i * P])
                for i in range(bs)]
        if self._batch_cost_s > 0:
            time.sleep(self._batch_cost_s)
        if self._pool_workers == 1:
            # inline serial decode: a 1-worker pool adds one cross-thread
            # handoff per record for zero parallelism — ruinous on a
            # starved host where every wakeup costs a scheduler quantum
            rows = [self._decode_one(g) for g in gids]
        else:
            rows = list(self._pool.map(self._decode_one, gids))
        good = next((r for r in rows if r is not None), None)
        if good is None:
            shard, entry = stream.locate(gids[0])
            raise _recordio.RecordCorruptError(
                f"every record of a {bs}-row batch failed verification "
                f"(first: key {entry.key} in {shard.rec_path}) — the "
                "skip policy substitutes single bad rows, not whole "
                "batches", path=shard.rec_path, key=entry.key,
                offset=entry.offset)
        rows = [r if r is not None else good for r in rows]
        if self._bucket_edges is not None:
            data, label, length = self._bucket_stack(rows)
        else:
            length = None
            data = _np.stack([r[0] for r in rows])
            label = _np.stack([r[1] for r in rows])
        if label.ndim == 2 and label.shape[1] == 1:
            label = label.reshape(bs)
        self._cursor = base + bs * P
        return StreamBatch(data, label, self.state(), length=length)

    def _bucket_stack(self, rows):
        """Pad variable-length rows to the smallest bucket edge that
        fits the batch's longest row and stack. Labels that are
        per-token sequences (row length == data row length) pad along
        with the data; per-example labels stack unchanged. Returns
        (data, label, real_lengths)."""
        lens = [int(_np.shape(r[0])[0]) for r in rows]
        need = max(lens)
        edge = next((e for e in self._bucket_edges if e >= need), None)
        if edge is None:
            raise MXNetError(
                f"a {need}-token row exceeds the largest bucket edge "
                f"{self._bucket_edges[-1]}; extend bucket_edges / "
                "MXNET_TPU_DATA_BUCKET_EDGES or truncate at decode "
                "(fixed bucket shapes are the no-retrace contract, "
                "docs/data.md)")

        def pad(a):
            a = _np.asarray(a)
            if a.shape[0] == edge:
                return a
            width = [(0, edge - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            return _np.pad(a, width, constant_values=self._bucket_pad)

        seq_labels = all(
            _np.ndim(r[1]) >= 1 and _np.shape(r[1])[0] == n
            for r, n in zip(rows, lens))
        data = _np.stack([pad(r[0]) for r in rows])
        label = (_np.stack([pad(r[1]) for r in rows]) if seq_labels
                 else _np.stack([_np.asarray(r[1]) for r in rows]))
        _STATS["io_bucket_batches"] += 1
        _STATS["io_bucket_pad_rows"] += sum(1 for n in lens if n != edge)
        return data, label, _np.asarray(lens, dtype=_np.int32)

    def _decode_one(self, gid):
        payload = self.stream.read(gid)
        if payload is None:
            return None
        header, content = _recordio.unpack(payload)
        return self.decode(header, content)

    # -------------------------------------------------------------- resume

    def state(self):
        """The resume token: everything needed to re-produce the exact
        remaining batch stream — on this rank, on a freshly-started
        replacement, or re-partitioned over a DIFFERENT ``num_parts``
        after a mesh shrink (``global_cursor`` is rank-agnostic; only
        batches fully handed out are counted). JSON-serializable; lands
        in the checkpoint manifest (docs/resilience.md)."""
        return {"version": STATE_VERSION,
                "epoch": int(self._epoch),
                "global_cursor": int(self._cursor),
                "epochs_done": int(self._epochs_done),
                "batch_size": int(self.batch_size),
                "num_parts": int(self.stream.num_parts),
                "seed": int(self.stream.seed),
                "shuffle": bool(self.stream.shuffle),
                "chunk_records": int(self.stream.chunk_records),
                **self.stream.identity()}

    def restore(self, state):
        """Resume from a token produced by :meth:`state` (possibly under
        a different ``num_parts``). The dataset identity and the order
        parameters (seed / shuffle / chunk size) must match — they
        define the sequence being resumed; a mismatch raises instead of
        silently re-sampling."""
        state = dict(state)
        if state.get("version") != STATE_VERSION:
            raise ValueError(
                f"unsupported stream-state version "
                f"{state.get('version')!r} (this build writes "
                f"{STATE_VERSION})")
        ident = self.stream.identity()
        for key in ("shards", "num_records"):
            if state.get(key) != ident[key]:
                raise ValueError(
                    f"stream state was saved over a different dataset "
                    f"({key}: {state.get(key)!r} != {ident[key]!r})")
        for key, have in (("seed", self.stream.seed),
                          ("shuffle", self.stream.shuffle),
                          ("chunk_records", self.stream.chunk_records),
                          ("batch_size", self.batch_size)):
            if state.get(key) != have:
                raise ValueError(
                    f"stream state {key}={state.get(key)!r} does not "
                    f"match this iterator's {key}={have!r}; the resumed "
                    "sequence would differ from the saved one")
        cursor = int(state["global_cursor"])
        if not 0 <= cursor <= self.stream.num_records:
            raise ValueError(f"stream-state cursor {cursor} out of range")
        self._epoch = int(state["epoch"])
        self._cursor = cursor
        self._epochs_done = int(state.get("epochs_done", 0))
        self._order = None
        _STATS["io_stream_resumes"] += 1
        return self

    def position(self):
        """Lightweight live-position snapshot (alert evidence)."""
        return {"epoch": int(self._epoch),
                "global_cursor": int(self._cursor),
                "num_records": int(self.stream.num_records),
                "part_index": int(self.stream.part_index),
                "num_parts": int(self.stream.num_parts)}


# --------------------------------------------------------- device prefetch

_DONE = object()


class DevicePrefetcher:
    """Double-buffered device prefetch over a :class:`StreamBatchIter`.

    A daemon worker pulls host batches from ``it`` and ``device_put``\\ s
    them (with the mesh's batch ``NamedSharding`` when given — the
    placement ``ShardedTrainer.batch_sharding`` exposes, so the step's
    own device_put is skipped) into a bounded ring of
    ``depth`` batches (``MXNET_TPU_DATA_PREFETCH``, default 2;
    0 = synchronous passthrough, no thread). While the captured step
    executes on device, the worker decodes and transfers the NEXT
    batches — ``__next__`` pops an already-resident ``(x, y)`` and the
    ``step.data_wait`` span collapses to the queue sync.

    ``state()`` is the resume token of the last batch HANDED TO THE
    CONSUMER: prefetched-but-unconsumed ring contents are deliberately
    not counted, so a kill-resume discards (and deterministically
    regenerates) them — never replays a consumed sample.
    """

    def __init__(self, it, sharding=None, depth=None):
        if depth is None:
            depth = int(os.environ.get("MXNET_TPU_DATA_PREFETCH", "2"))
        self.depth = max(0, int(depth))
        self._it = it
        self._sharding = sharding
        self.last_state = it.state()
        self._finished = False
        self._q = None
        self._stop = None
        self._thread = None
        if self.depth:
            self._start()

    @classmethod
    def for_trainer(cls, trainer, it, depth=None):
        """Prefetch onto ``trainer``'s batch placement (works with a
        ``ShardedTrainer`` or a ``capture.CapturedShardedStep`` — both
        expose ``batch_sharding``)."""
        return cls(it, sharding=getattr(trainer, "batch_sharding", None),
                   depth=depth)

    # ------------------------------------------------------------- worker

    def _start(self):
        self._q = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._finished = False
        self._thread = threading.Thread(
            target=self._worker, name="mxnet-tpu-data-prefetch",
            daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for batch in self._it:
                item = (self._put(batch), batch.state)
                if not self._enqueue(item):
                    return
            self._enqueue(_DONE)
        except BaseException as e:  # surfaced on the consumer's next()
            self._enqueue(e)

    def _enqueue(self, item):
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                _STATS["io_prefetch_depth"] = self._q.qsize()
                return True
            except queue.Full:
                continue
        return False

    def _put(self, batch):
        import jax

        with _obs_trace.span("data.h2d", rows=len(batch.data)):
            arrs = [batch.data, batch.label]
            if batch.length is not None:  # bucketed text: real lengths
                arrs.append(batch.length)
            if self._sharding is not None:
                out = [jax.device_put(a, self._sharding) for a in arrs]
            else:
                out = [jax.device_put(a) for a in arrs]
        # bucketed batches hand (x, y, lengths) to the consumer; the
        # common image path keeps its (x, y) contract
        return tuple(out)

    # ----------------------------------------------------------- consumer

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        if self.depth != 0 and self._q is None:
            raise RuntimeError("DevicePrefetcher is closed")
        # the time the training loop stalls on input, both modes: the
        # ring pop (prefetching — collapses to the queue sync) or the
        # whole inline decode+transfer (passthrough — the un-overlapped
        # cost the stream bench's prefetch-off phase measures)
        with _obs_trace.span("step.data_wait"):
            if self.depth == 0:
                batch = next(self._it)  # StopIteration ends the stream
                xy, state = self._put(batch), batch.state
            else:
                item = self._q.get()
                _STATS["io_prefetch_depth"] = self._q.qsize()
                if item is _DONE:
                    self._finished = True
                    raise StopIteration
                if isinstance(item, BaseException):
                    self._finished = True
                    raise item
                xy, state = item
        self.last_state = state
        return xy

    # ------------------------------------------------------------- resume

    def state(self):
        return dict(self.last_state)

    def restore(self, state):
        """Stop the worker, rewind the source to ``state``, and restart:
        whatever the ring held is discarded and regenerates from the
        restored position."""
        self.close()
        self._it.restore(state)
        self.last_state = self._it.state()
        self._finished = False
        if self.depth:
            self._start()
        return self

    def position(self):
        return self._it.position()

    def close(self, timeout=5.0):
        """Stop the prefetch worker and drain the ring. Raises if the
        worker did not exit within ``timeout`` — restore() must never
        start a second worker while an orphaned one is still advancing
        the SAME source iterator (two cursors, broken determinism);
        close() can be retried after the stuck decode finishes."""
        if self._thread is None:
            return
        self._stop.set()
        while True:  # unblock a worker stuck in put()
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RuntimeError(
                f"prefetch worker still running after {timeout}s "
                "(wedged in a slow decode?); retry close() before "
                "restoring or restarting this prefetcher")
        self._thread = None
        self._q = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
