"""ImageRecordIter — the RecordIO image training pipeline.

Capability parity with the reference's `mx.io.ImageRecordIter`
(src/io/iter_image_recordio_2.cc: parsing :708, decode/augment workers,
double-buffered batch assembly :880), re-designed for the TPU consumer: the
unit of hand-off is a whole assembled float32 batch, produced by the native
C++ library in src/io/record_pipeline.cc (thread-pool decode + a ring of
prefetched batch slots) and borrowed zero-copy over ctypes.

A pure-Python fallback (_PyPipeline: PIL decode, batches assembled on a
thread pool) provides the same semantics when the native library can't be
built, so the API is always available; throughput work belongs to the
native path.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import warnings

import numpy as _np

from ..base import MXNetError
from ..ndarray import ndarray as _nd
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "load_native", "native_available"]


class _CConfig(ctypes.Structure):
    # Field order/types mirror PipelineConfig in src/io/record_pipeline.cc.
    _fields_ = [
        ("batch_size", ctypes.c_int32),
        ("channels", ctypes.c_int32),
        ("height", ctypes.c_int32),
        ("width", ctypes.c_int32),
        ("label_width", ctypes.c_int32),
        ("shuffle", ctypes.c_int32),
        ("seed", ctypes.c_uint32),
        ("num_threads", ctypes.c_int32),
        ("prefetch", ctypes.c_int32),
        ("rand_mirror", ctypes.c_int32),
        ("rand_crop", ctypes.c_int32),
        ("random_resized_crop", ctypes.c_int32),
        ("min_area", ctypes.c_float),
        ("max_area", ctypes.c_float),
        ("min_aspect", ctypes.c_float),
        ("max_aspect", ctypes.c_float),
        ("resize", ctypes.c_int32),
        ("mean", ctypes.c_float * 4),
        ("std", ctypes.c_float * 4),
        ("part_index", ctypes.c_int32),
        ("num_parts", ctypes.c_int32),
        ("round_batch", ctypes.c_int32),
        ("layout", ctypes.c_int32),
    ]


_lib = None
_lib_tried = False
_lib_lock = threading.Lock()


def _lib_path():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "_lib", "libmxtpu_io.so")


def load_native():
    """Load (building if necessary) the native pipeline library."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        path = _lib_path()
        if not os.path.exists(path):
            src = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "src", "io")
            if os.path.isdir(src):
                try:
                    # Serialize the build across processes (multi-rank
                    # launches all race here on a fresh checkout).
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    import fcntl

                    with open(path + ".buildlock", "w") as lock:
                        fcntl.flock(lock, fcntl.LOCK_EX)
                        if not os.path.exists(path):
                            subprocess.run(["make", "-C", src], check=True,
                                           capture_output=True)
                except (OSError, subprocess.CalledProcessError) as e:
                    warnings.warn(f"native data pipeline build failed ({e}); "
                                  "falling back to the Python loader")
                    return None
        if not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            warnings.warn(f"cannot load {path}: {e}")
            return None
        lib.mxtpu_pipeline_create.restype = ctypes.c_void_p
        lib.mxtpu_pipeline_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(_CConfig)]
        lib.mxtpu_pipeline_next.restype = ctypes.c_int
        lib.mxtpu_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int)]
        lib.mxtpu_pipeline_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.mxtpu_pipeline_reset.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipeline_size.restype = ctypes.c_int64
        lib.mxtpu_pipeline_size.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipeline_batches.restype = ctypes.c_int64
        lib.mxtpu_pipeline_batches.argtypes = [ctypes.c_void_p]
        lib.mxtpu_last_error.restype = ctypes.c_char_p
        _lib = lib
        return _lib


def native_available():
    return load_native() is not None


def _build_config(batch_size, data_shape, label_width, shuffle, seed,
                  preprocess_threads, prefetch_buffer, rand_mirror, rand_crop,
                  random_resized_crop, min_random_area, max_random_area,
                  min_aspect_ratio, max_aspect_ratio, resize, mean, std,
                  part_index, num_parts, round_batch, layout):
    cfg = _CConfig()
    cfg.batch_size = batch_size
    cfg.channels, cfg.height, cfg.width = data_shape
    cfg.label_width = label_width
    cfg.shuffle = int(bool(shuffle))
    cfg.seed = seed & 0xFFFFFFFF
    cfg.num_threads = preprocess_threads
    cfg.prefetch = prefetch_buffer
    cfg.rand_mirror = int(bool(rand_mirror))
    cfg.rand_crop = int(bool(rand_crop))
    cfg.random_resized_crop = int(bool(random_resized_crop))
    cfg.min_area, cfg.max_area = min_random_area, max_random_area
    cfg.min_aspect, cfg.max_aspect = min_aspect_ratio, max_aspect_ratio
    cfg.resize = resize
    for i in range(4):
        cfg.mean[i] = mean[i] if i < len(mean) else 0.0
        # std=0 means "unset" in the reference's parameterization; coerce
        # here so the native and Python backends agree.
        cfg.std[i] = (std[i] or 1.0) if i < len(std) else 1.0
    cfg.part_index, cfg.num_parts = part_index, num_parts
    cfg.round_batch = int(bool(round_batch))
    cfg.layout = layout
    return cfg


class ImageRecordIter(DataIter):
    """RecordIO image iterator (reference surface: mx.io.ImageRecordIter,
    CreateDataIter registration in src/io/iter_image_recordio_2.cc).

    Parameters follow the reference: ``path_imgrec``, ``path_imgidx``,
    ``data_shape`` (C, H, W), ``batch_size``, ``shuffle``, ``rand_crop``,
    ``rand_mirror``, ``random_resized_crop`` (+ ``min_random_area``/
    ``max_random_area``/``min_aspect_ratio``/``max_aspect_ratio``),
    ``resize`` (shorter side), ``mean_r/g/b``, ``std_r/g/b``,
    ``label_width``, ``preprocess_threads``, ``prefetch_buffer``,
    ``num_parts``/``part_index`` (sharding), ``round_batch``, ``seed``.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, random_resized_crop=False,
                 min_random_area=0.08, max_random_area=1.0,
                 min_aspect_ratio=3.0 / 4.0, max_aspect_ratio=4.0 / 3.0,
                 resize=0, mean_r=0.0, mean_g=0.0, mean_b=0.0, mean_a=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, std_a=1.0, label_width=1,
                 preprocess_threads=4, prefetch_buffer=4, num_parts=1,
                 part_index=0, round_batch=True, seed=0,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 force_python=False, **kwargs):
        super().__init__(batch_size)
        if kwargs:
            warnings.warn(f"ImageRecordIter: ignoring unsupported arguments "
                          f"{sorted(kwargs)}")
        data_shape = tuple(int(d) for d in data_shape)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, height, width)")
        if data_shape[0] not in (1, 3):
            raise MXNetError("channels must be 1 (grayscale) or 3 (RGB), "
                             f"got {data_shape[0]}")
        self._data_shape = data_shape
        self._label_width = label_width
        self._data_name, self._label_name = data_name, label_name
        self._dtype = _np.dtype(dtype)
        self._pad = 0
        mean = (mean_r, mean_g, mean_b, mean_a)
        std = (std_r, std_g, std_b, std_a)
        cfg = _build_config(
            batch_size, data_shape, label_width, shuffle, seed,
            preprocess_threads, prefetch_buffer, rand_mirror, rand_crop,
            random_resized_crop, min_random_area, max_random_area,
            min_aspect_ratio, max_aspect_ratio, resize, mean, std,
            part_index, num_parts, round_batch, layout=0)
        lib = None if force_python else load_native()
        if lib is not None:
            self._impl = _NativePipeline(lib, path_imgrec, path_imgidx, cfg)
        else:
            self._impl = _PyPipeline(path_imgrec, cfg,
                                     idx_path=path_imgidx)

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._data_shape, self._dtype)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self._label_width == 1
                 else (self.batch_size, self._label_width))
        return [DataDesc(self._label_name, shape, self._dtype)]

    def __len__(self):
        return self._impl.num_batches

    @property
    def num_samples(self):
        return self._impl.num_samples

    def reset(self):
        self._impl.reset()

    def next(self):
        out = self._impl.next()
        if out is None:
            raise StopIteration
        data, label, pad = out
        self._pad = pad
        if self._label_width == 1:
            label = label.reshape(self.batch_size)
        if self._dtype != _np.float32:
            data = data.astype(self._dtype)
            label = label.astype(self._dtype)
        return DataBatch(data=[_nd.array(data, dtype=data.dtype)],
                         label=[_nd.array(label, dtype=label.dtype)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._next_batch.data

    def getlabel(self):
        return self._next_batch.label

    def getpad(self):
        return self._pad


class _NativePipeline:
    """ctypes driver for src/io/record_pipeline.cc."""

    def __init__(self, lib, rec_path, idx_path, cfg):
        self._lib = lib
        self._cfg = cfg
        self._h = lib.mxtpu_pipeline_create(
            rec_path.encode(), (idx_path or "").encode(), ctypes.byref(cfg))
        if not self._h:
            raise MXNetError("native pipeline: " +
                             lib.mxtpu_last_error().decode())
        self.num_samples = lib.mxtpu_pipeline_size(self._h)
        self.num_batches = lib.mxtpu_pipeline_batches(self._h)
        self._dshape = (cfg.batch_size, cfg.channels, cfg.height, cfg.width)
        self._lshape = (cfg.batch_size, cfg.label_width)

    def next(self):
        data_p = ctypes.POINTER(ctypes.c_float)()
        label_p = ctypes.POINTER(ctypes.c_float)()
        pad = ctypes.c_int()
        slot = self._lib.mxtpu_pipeline_next(
            self._h, ctypes.byref(data_p), ctypes.byref(label_p),
            ctypes.byref(pad))
        if slot < 0:
            return None
        try:
            # One host copy out of the borrowed slot. Deliberately NOT a
            # zero-copy device_put: on the CPU backend jax may alias the
            # host buffer indefinitely, which would race with slot reuse.
            data = _np.ctypeslib.as_array(data_p, shape=self._dshape).copy()
            label = _np.ctypeslib.as_array(label_p, shape=self._lshape).copy()
        finally:
            self._lib.mxtpu_pipeline_release(self._h, slot)
        return data, label, pad.value

    def reset(self):
        self._lib.mxtpu_pipeline_reset(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.mxtpu_pipeline_destroy(self._h)
            self._h = None


class _PyPipeline:
    """Pure-Python fallback with identical batch semantics (PIL decode)."""

    def __init__(self, rec_path, cfg, idx_path=None):
        self._cfg = cfg
        # offset of each logical record's first frame: from the .idx
        # offset index when one exists (range reads, no full-file scan —
        # the same index the streaming layer and the native pipeline
        # consume), else a sequential framing scan
        self._records = self._load_index_offsets(rec_path, idx_path)
        if self._records is None:
            self._records = self._scan_offsets(rec_path)
        self._rec_path = rec_path
        self._tls = threading.local()
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=max(1, cfg.num_threads))
        if cfg.num_parts > 1:
            self._records = self._records[cfg.part_index::cfg.num_parts]
        if not self._records:
            raise MXNetError("no records in shard")
        self.num_samples = len(self._records)
        bs = cfg.batch_size
        self.num_batches = ((self.num_samples + bs - 1) // bs
                            if cfg.round_batch else self.num_samples // bs)
        if self.num_batches == 0:  # match the native backend's behavior
            raise MXNetError(
                "fewer records than batch_size and round_batch=0")
        self._order = _np.arange(self.num_samples)
        self._epoch = 0
        self._start_epoch(first=True)

    @staticmethod
    def _load_index_offsets(rec_path, idx_path):
        """Record offsets from the .idx index, or None when the index is
        absent or fails a cheap sanity check (a stale index must fall
        back to the scan, like the native reader does)."""
        if not idx_path or not os.path.isfile(idx_path):
            return None
        from ..recordio import load_index, read_logical_record

        try:
            offsets = [e.offset for e in load_index(idx_path)]
        except (OSError, ValueError):
            return None
        size = os.path.getsize(rec_path)
        if not offsets or offsets != sorted(offsets) \
                or offsets[0] != 0 or offsets[-1] >= size:
            return None
        # the index must reach EOF: an index from an earlier, SHORTER
        # pack of the same data passes every offset check but would
        # silently drop the trailing records — verify the record framed
        # at the last offset ends exactly at the file size
        try:
            with open(rec_path, "rb") as f:
                f.seek(offsets[-1])
                if read_logical_record(f) is None or f.tell() != size:
                    return None
        except (OSError, ValueError):
            return None
        return offsets

    @staticmethod
    def _scan_offsets(rec_path):
        from ..recordio import _decode_flag_len, _kMagic

        records = []
        with open(rec_path, "rb") as f:
            off = 0
            in_split = False
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                magic, fl = struct.unpack("<II", hdr)
                if magic != _kMagic:
                    raise MXNetError("bad record magic")
                cflag, length = _decode_flag_len(fl)
                if not in_split:
                    records.append(off)
                    in_split = cflag == 1  # kBegin
                elif cflag == 3:  # kEnd
                    in_split = False
                elif cflag != 2:  # not kMiddle
                    raise MXNetError("bad record framing")
                skip = (length + 3) & ~3
                f.seek(off + 8 + skip)
                off += 8 + skip
            if in_split:
                raise MXNetError("truncated split record")
        return records

    def _start_epoch(self, first=False):
        if not first:
            self._epoch += 1
        if self._cfg.shuffle:
            _np.random.RandomState(
                self._cfg.seed + self._epoch).shuffle(self._order)
        self._cursor = 0

    def _file(self):
        # One handle per pool thread: seek/read pairs must not interleave.
        f = getattr(self._tls, "f", None)
        if f is None:
            f = open(self._rec_path, "rb")
            self._tls.f = f
        return f

    def _read_logical(self, off):
        """Read the logical record at `off` (recordio.read_logical_record is
        the single framing parser)."""
        from ..recordio import read_logical_record

        f = self._file()
        f.seek(off)
        return read_logical_record(f)

    def _decode(self, rec_i, rng):
        from io import BytesIO

        from PIL import Image

        from ..recordio import unpack

        cfg = self._cfg
        buf = self._read_logical(self._records[rec_i])
        header, payload = unpack(buf)
        lab = _np.atleast_1d(_np.asarray(header.label, dtype=_np.float32))
        label = _np.zeros(cfg.label_width, dtype=_np.float32)
        label[:min(cfg.label_width, lab.size)] = lab[:cfg.label_width]

        img = Image.open(BytesIO(payload))
        img = img.convert("L" if cfg.channels == 1 else "RGB")
        W, H = cfg.width, cfg.height
        if cfg.random_resized_crop:
            src_area = img.size[0] * img.size[1]
            done = False
            for _ in range(10):
                area = src_area * rng.uniform(cfg.min_area, cfg.max_area)
                aspect = _np.exp(rng.uniform(_np.log(cfg.min_aspect),
                                             _np.log(cfg.max_aspect)))
                cw = int(round(_np.sqrt(area * aspect)))
                ch = int(round(_np.sqrt(area / aspect)))
                if 0 < cw <= img.size[0] and 0 < ch <= img.size[1]:
                    x = rng.randint(0, img.size[0] - cw + 1)
                    y = rng.randint(0, img.size[1] - ch + 1)
                    img = img.crop((x, y, x + cw, y + ch)).resize((W, H))
                    done = True
                    break
            if not done:
                side = min(img.size)
                x = (img.size[0] - side) // 2
                y = (img.size[1] - side) // 2
                img = img.crop((x, y, x + side, y + side)).resize((W, H))
        else:
            if cfg.resize > 0:
                scale = cfg.resize / min(img.size)
                img = img.resize((max(W, int(round(img.size[0] * scale))),
                                  max(H, int(round(img.size[1] * scale)))))
            if img.size != (W, H):
                if img.size[0] < W or img.size[1] < H:
                    img = img.resize((W, H))
                elif cfg.rand_crop:
                    x = rng.randint(0, img.size[0] - W + 1)
                    y = rng.randint(0, img.size[1] - H + 1)
                    img = img.crop((x, y, x + W, y + H))
                else:
                    x = (img.size[0] - W) // 2
                    y = (img.size[1] - H) // 2
                    img = img.crop((x, y, x + W, y + H))
        arr = _np.asarray(img, dtype=_np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if cfg.rand_mirror and rng.randint(0, 2):
            arr = arr[:, ::-1]
        mean = _np.array([cfg.mean[c] for c in range(cfg.channels)],
                         dtype=_np.float32)
        std = _np.array([cfg.std[c] for c in range(cfg.channels)],
                        dtype=_np.float32)
        arr = (arr - mean) / std
        return arr.transpose(2, 0, 1), label  # NCHW

    def next(self):
        cfg = self._cfg
        bs = cfg.batch_size
        if self._cursor >= self.num_batches:
            return None
        b = self._cursor
        data = _np.zeros((bs, cfg.channels, cfg.height, cfg.width),
                         dtype=_np.float32)
        label = _np.zeros((bs, cfg.label_width), dtype=_np.float32)
        pad = max(0, (b + 1) * bs - self.num_samples)

        def _one(pos):
            sample = b * bs + pos
            rec_i = self._order[sample % self.num_samples]
            rng = _np.random.RandomState(
                (cfg.seed * 2654435761 + self._epoch * 97 + sample)
                & 0xFFFFFFFF)
            data[pos], label[pos] = self._decode(rec_i, rng)

        # Per-sample RNGs are independently seeded, so pool scheduling
        # doesn't affect determinism.
        list(self._pool.map(_one, range(bs)))
        self._cursor += 1
        return data, label, pad

    def reset(self):
        self._start_epoch()
