"""Data iterators (parity: python/mxnet/io/io.py).

DataIter ABC (io.py:180), NDArrayIter (:491, pad/roll-over), ResizeIter,
PrefetchingIter (background-thread double buffering — the Python face of the
reference's dmlc::ThreadedIter), and factory-style iterators backed by the
native pipeline in src/ (ImageRecordIter) or numpy (MNISTIter, CSVIter).
"""
from __future__ import annotations

import threading
from collections import namedtuple

import numpy as _np

from ..base import MXNetError
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = (_np.float32, "NCHW")


def _data_desc(name, arr):
    return DataDesc(name, tuple(arr.shape), arr.dtype)


class DataBatch:
    """One mini-batch (io.py:116)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        return f"DataBatch: data shapes {shapes} pad={self.pad}"


class DataIter:
    """Iterator ABC (io.py:180)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty and len(data) == 0:
            raise MXNetError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError("data must be NDArray, numpy array, list or dict")
    return [(k, _nd.array(v) if not isinstance(v, NDArray) else v)
            for k, v in data.items()]


class NDArrayIter(DataIter):
    """In-memory iterator with pad/discard/roll_over (io.py:491)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = _np.arange(self.num_data)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        start = self.cursor
        end = min(start + self.batch_size, self.num_data)
        ids = self.idx[start:end]
        if len(ids) < self.batch_size:  # pad from the front
            extra = self.batch_size - len(ids)
            ids = _np.concatenate([ids, self.idx[:extra]])
        out = []
        for _, v in arrays:
            np_v = v.asnumpy()
            out.append(_nd.array(np_v[ids], dtype=np_v.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator's epoch length (io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch (io.py PrefetchingIter; the Python analogue
    of src/io/iter_prefetcher.h's dmlc::ThreadedIter double buffer)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        iters = iters if isinstance(iters, list) else [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.n_iter = len(iters)
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self.started = True
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=[i], daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([(b.label or []) for b in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class MXDataIter(DataIter):
    """Placeholder for native-pipeline-backed iterators."""

    def __init__(self, *a, **kw):
        raise MXNetError("this iterator requires the native data pipeline; "
                         "use ImageRecordIter / NDArrayIter")


def MNISTIter(image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
              batch_size=128, shuffle=True, flat=False, data_name="data",
              label_name="softmax_label", **kwargs):
    """Parity: src/io/iter_mnist.cc — reads idx-format MNIST files."""
    import gzip
    import os
    import struct

    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    with _open(label) as f:
        magic, num = struct.unpack(">II", f.read(8))
        lbl = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.float32)
    with _open(image) as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        img = _np.frombuffer(f.read(), dtype=_np.uint8).reshape(num, rows, cols)
    img = img.astype(_np.float32) / 255.0
    data = img.reshape(num, -1) if flat else img.reshape(num, 1, rows, cols)
    return NDArrayIter(data, lbl, batch_size=batch_size, shuffle=shuffle,
                       data_name=data_name, label_name=label_name)


def CSVIter(data_csv, data_shape, label_csv=None, label_shape=(1,),
            batch_size=128, **kwargs):
    """Parity: src/io/iter_csv.cc."""
    data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
    data = data.reshape((-1,) + tuple(data_shape))
    label = None
    if label_csv is not None:
        label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
    return NDArrayIter(data, label, batch_size=batch_size, **kwargs)


def ImageRecordIter(*args, **kwargs):
    """RecordIO image pipeline (parity: src/io/iter_image_recordio_2.cc).
    Provided by the native loader in mxnet_tpu.io.record_pipeline."""
    from .record_pipeline import ImageRecordIter as _Impl

    return _Impl(*args, **kwargs)
