from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MXDataIter, ImageRecordIter, MNISTIter,
                 CSVIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MXDataIter", "ImageRecordIter", "MNISTIter",
           "CSVIter"]
