from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MXDataIter, ImageRecordIter, MNISTIter,
                 CSVIter)
from .stream import (RecordStream, StreamBatchIter, StreamBatch,
                     DevicePrefetcher)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MXDataIter", "ImageRecordIter", "MNISTIter",
           "CSVIter", "RecordStream", "StreamBatchIter", "StreamBatch",
           "DevicePrefetcher"]
