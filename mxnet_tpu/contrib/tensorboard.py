"""TensorBoard logging callback.

Parity: python/mxnet/contrib/tensorboard.py (LogMetricsCallback). Uses any
SummaryWriter-compatible object (tensorboardX / torch.utils.tensorboard —
torch is available in this environment); constructing without one raises
with instructions rather than failing at import.
"""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Log training speed and metrics to TensorBoard every batch
    (tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        try:
            from torch.utils.tensorboard import SummaryWriter

            self.summary_writer = SummaryWriter(logging_dir)
        except ImportError as e:
            raise ImportError(
                "LogMetricsCallback needs a SummaryWriter backend "
                "(torch.utils.tensorboard or tensorboardX)") from e
        self.step = 0

    def __call__(self, param):
        """BatchEndParam callback."""
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
        self.step += 1
