"""SVRG optimization (contrib).

Capability parity with python/mxnet/contrib/svrg_optimization/
(SVRGModule :30, SVRGOptimizer): Stochastic Variance-Reduced Gradient —
every `update_freq` epochs a snapshot of the weights is taken and the
full-dataset gradient `mu` at that snapshot is computed; each minibatch
then steps with the variance-reduced gradient
``g_i(w) - g_i(w_snapshot) + mu``.

TPU-native form: the snapshot network is a second bound executor over the
same symbol (both are cached XLA executables), mu lives on device as
NDArrays, and the gradient algebra is a few fused device ops per
parameter — no special optimizer subclass is needed, so ANY registered
optimizer gets variance reduction. Single-context only (multi-device SVRG
belongs to the sharded trainer path, not per-executor bookkeeping).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient correction (svrg_module.py:30).

    Parameters mirror Module, plus ``update_freq``: the number of epochs
    between full-gradient snapshots.
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, fixed_param_names=None, state_names=None,
                 update_freq=2, **kwargs):
        if isinstance(context, (list, tuple)) and len(context) > 1:
            raise MXNetError(
                "SVRGModule supports a single context; for multi-device "
                "training use parallel.ShardedTrainer (GSPMD) instead")
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context,
                         fixed_param_names=fixed_param_names,
                         state_names=state_names, **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context,
                               fixed_param_names=fixed_param_names,
                               state_names=state_names)
        self._mu = None  # device NDArrays: full gradient at the snapshot

    # ------------------------------------------------------------ lifecycle
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return  # silent: fit() re-enters bind once per inner epoch
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def _take_snapshot(self):
        """Copy the live weights into the snapshot module. Called ONLY by
        update_full_grads — the snapshot must move in lockstep with mu, or
        the correction g(w) - g(w_snap) + mu becomes biased."""
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux, allow_missing=False,
                                 force_init=True)

    # --------------------------------------------------------- full gradient
    def update_full_grads(self, train_data):
        """Snapshot the weights and compute mu = mean gradient over
        `train_data` at the snapshot (svrg_module.py update_full_grads).
        mu is accumulated and kept on device."""
        self._take_snapshot()
        train_data.reset()
        acc = {}
        total_w = 0.0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            # a padded final batch duplicates front-of-epoch samples
            # (io.py NDArrayIter pad); down-weight its contribution so mu
            # stays an (approximately) unbiased full-dataset gradient
            pad = getattr(batch, "pad", 0) or 0
            bs = batch.data[0].shape[0]
            w = (bs - pad) / bs
            for name, g in zip(self._mod_aux._param_names,
                               self._grads_of(self._mod_aux)):
                if g is None:
                    continue
                gw = g * w if w != 1.0 else g.copy()
                acc[name] = gw if name not in acc else acc[name] + gw
            total_w += w
        if total_w == 0.0:
            raise MXNetError("update_full_grads: empty data iterator")
        self._mu = {k: v / total_w for k, v in acc.items()}
        train_data.reset()  # leave the iterator fresh for the epoch loop

    @staticmethod
    def _grads_of(mod):
        return [mod._execs[0].grad_dict.get(n) for n in mod._param_names]

    # ------------------------------------------------------------- training
    def forward_backward(self, data_batch):
        """Variance-reduced step: main grads become
        g(w) - g(w_snap) + mu (svrg_module.py _update_svrg_gradients)."""
        self.forward(data_batch, is_train=True)
        self.backward()
        if self._mu is None:
            return
        self._mod_aux.forward(data_batch, is_train=True)
        self._mod_aux.backward()
        for name, g_main, g_snap in zip(
                self._param_names, self._grads_of(self),
                self._grads_of(self._mod_aux)):
            if g_main is None or g_snap is None or name not in self._mu:
                continue
            g_main._set_data((g_main - g_snap + self._mu[name])._data)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            num_epoch=None, initializer=None, arg_params=None,
            aux_params=None, allow_missing=False, force_rebind=False,
            force_init=False, kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),), begin_epoch=0,
            **kwargs):
        """Module.fit with a full-gradient refresh every update_freq
        epochs. bind/init/optimizer happen once up front (reference
        structure), so epoch 0 is already variance-reduced; the inner
        one-epoch fits re-enter those as no-ops and keep epoch numbering
        for callbacks/logs."""
        from ..initializer import Uniform

        if num_epoch is None:
            raise MXNetError("num_epoch is required")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        for epoch in range(begin_epoch, num_epoch):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            super().fit(train_data, eval_data=eval_data,
                        eval_metric=eval_metric, begin_epoch=epoch,
                        num_epoch=epoch + 1, kvstore=kvstore,
                        optimizer=optimizer,
                        optimizer_params=optimizer_params, **kwargs)
        return self
