"""Symbol graph -> ONNX model serialization.

Parity: python/mxnet/contrib/onnx/mx2onnx/export_onnx.py +
_op_translations.py in the reference, rebuilt against this framework's
Symbol graph (symbol/symbol.py) and the self-contained wire codec
(proto.py) — the environment has no onnx package, so the ModelProto is
emitted directly.

Opset: 11 (attribute conventions below follow it — Reshape/Pad/Slice/Clip
take tensor inputs, Dropout's ratio is an attribute).
"""
from __future__ import annotations

import json

import numpy as np

from . import proto as P

ONNX_FLOAT, ONNX_INT64 = 1, 7
_ATTR_FLOAT, _ATTR_INT, _ATTR_STR, _ATTR_FLOATS, _ATTR_INTS, _ATTR_STRS = \
    1, 2, 3, 6, 7, 8
OPSET = 11


# ---------------------------------------------------------------- protos

def _attr(name, val):
    b = P.emit_str(1, name)
    if isinstance(val, float):
        b += P.emit_float(2, val) + P.emit_int(20, _ATTR_FLOAT)
    elif isinstance(val, bool) or isinstance(val, (int, np.integer)):
        b += P.emit_int(3, int(val)) + P.emit_int(20, _ATTR_INT)
    elif isinstance(val, str):
        b += P.emit_bytes(4, val.encode()) + P.emit_int(20, _ATTR_STR)
    elif isinstance(val, (list, tuple)):
        if val and isinstance(val[0], float):
            b += b"".join(P.emit_float(7, v) for v in val)
            b += P.emit_int(20, _ATTR_FLOATS)
        else:
            b += b"".join(P.emit_int(8, int(v)) for v in val)
            b += P.emit_int(20, _ATTR_INTS)
    else:  # pragma: no cover
        raise TypeError(f"attribute {name}: {type(val)}")
    return P.emit_bytes(5, b)


def _node(op_type, inputs, outputs, name="", **attrs):
    b = b"".join(P.emit_str(1, i) for i in inputs)
    b += b"".join(P.emit_str(2, o) for o in outputs)
    if name:
        b += P.emit_str(3, name)
    b += P.emit_str(4, op_type)
    for k, v in attrs.items():
        b += _attr(k, v)
    return b


def _tensor(name, arr):
    arr = np.asarray(arr)
    if arr.dtype in (np.int32, np.int64):
        arr = arr.astype(np.int64)
        dtype = ONNX_INT64
    else:
        arr = arr.astype(np.float32)
        dtype = ONNX_FLOAT
    b = b"".join(P.emit_int(1, d) for d in arr.shape)
    b += P.emit_int(2, dtype)
    b += P.emit_str(8, name)
    b += P.emit_bytes(9, arr.tobytes())  # raw_data (little-endian)
    return b


def _value_info(name, shape, dtype=ONNX_FLOAT):
    dims = b"".join(
        P.emit_bytes(1, P.emit_int(1, d)) for d in shape)  # Dimension
    shape_proto = P.emit_bytes(2, dims)  # TensorShapeProto
    tensor_type = P.emit_bytes(1, P.emit_int(1, dtype) + shape_proto)
    return P.emit_str(1, name) + P.emit_bytes(2, tensor_type)


def _graph(nodes, name, initializers, inputs, outputs):
    b = b"".join(P.emit_bytes(1, n) for n in nodes)
    b += P.emit_str(2, name)
    b += b"".join(P.emit_bytes(5, t) for t in initializers)
    b += b"".join(P.emit_bytes(11, v) for v in inputs)
    b += b"".join(P.emit_bytes(12, v) for v in outputs)
    return b


def _model(graph):
    b = P.emit_int(1, 6)  # ir_version 6 <-> opset 11 era
    b += P.emit_str(2, "mxnet_tpu")
    b += P.emit_str(3, "1.6.0")
    b += P.emit_bytes(7, graph)
    b += P.emit_bytes(14, P.emit_str(1, "") + P.emit_int(2, OPSET))
    return b


# ------------------------------------------------------- op translations
#
# Each translator: fn(ctx, node_name, inputs, params) -> list[node bytes].
# `inputs` are resolved ONNX value names; output name == node_name.

def _pads2(pad):
    """Symbol pad tuple -> ONNX pads [x1b, x2b, ..., x1e, x2e]."""
    begins, ends = [], []
    for p in pad:
        if isinstance(p, (tuple, list)):
            begins.append(int(p[0]))
            ends.append(int(p[1]))
        else:
            begins.append(int(p))
            ends.append(int(p))
    return begins + ends


def _tuple_of(v, n=None):
    if v is None:
        return None
    if isinstance(v, (int, float)):
        v = (int(v),) * (n or 1)
    return tuple(v)


class _Ctx:
    """Export state: extra initializers created by translators, plus the
    input shapes of the node currently being translated (``in_shapes``,
    aligned with ``ins``; entries may be None when inference failed)."""

    def __init__(self):
        self.extra_init = []
        self._n = 0
        self.in_shapes = []

    def const(self, arr, hint="const"):
        name = f"__{hint}_{self._n}"
        self._n += 1
        self.extra_init.append(_tensor(name, arr))
        return name


def _t_convolution(ctx, name, ins, p):
    if p.get("layout") not in (None, "NCHW", "NCW", "NCDHW"):
        raise ValueError("ONNX export supports channels-first layouts only")
    kernel = _tuple_of(p.get("kernel"))
    nd = len(kernel)
    attrs = dict(kernel_shape=list(kernel),
                 strides=list(_tuple_of(p.get("stride") or 1, nd)),
                 dilations=list(_tuple_of(p.get("dilate") or 1, nd)),
                 group=int(p.get("num_group", 1)),
                 pads=_pads2(_tuple_of(p.get("pad") or 0, nd)))
    return [_node("Conv", ins, [name], name, **attrs)]


def _t_deconvolution(ctx, name, ins, p):
    kernel = _tuple_of(p.get("kernel"))
    nd = len(kernel)
    attrs = dict(kernel_shape=list(kernel),
                 strides=list(_tuple_of(p.get("stride") or 1, nd)),
                 dilations=list(_tuple_of(p.get("dilate") or 1, nd)),
                 group=int(p.get("num_group", 1)),
                 pads=_pads2(_tuple_of(p.get("pad") or 0, nd)))
    return [_node("ConvTranspose", ins, [name], name, **attrs)]


def _t_fullyconnected(ctx, name, ins, p):
    nodes = []
    data = ins[0]
    if p.get("flatten", True):
        nodes.append(_node("Flatten", [data], [name + "_flat"],
                           name + "_flat", axis=1))
        data = name + "_flat"
    if p.get("no_bias"):
        zero = ctx.const(np.zeros(int(p["num_hidden"]), np.float32), "zb")
        gemm_in = [data, ins[1], zero]
    else:
        gemm_in = [data, ins[1], ins[2]]
    nodes.append(_node("Gemm", gemm_in, [name], name, alpha=1.0, beta=1.0,
                       transA=0, transB=1))
    return nodes


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _t_activation(ctx, name, ins, p):
    return [_node(_ACT[p.get("act_type", "relu")], [ins[0]], [name], name)]


def _t_leakyrelu(ctx, name, ins, p):
    act = p.get("act_type", "leaky")
    slope = float(p.get("slope", 0.25))
    if act == "leaky":
        return [_node("LeakyRelu", [ins[0]], [name], name, alpha=slope)]
    if act == "elu":
        return [_node("Elu", [ins[0]], [name], name, alpha=slope)]
    if act == "selu":
        return [_node("Selu", [ins[0]], [name], name)]
    if act == "prelu":
        return [_node("PRelu", [ins[0], ins[1]], [name], name)]
    raise ValueError(f"LeakyReLU act_type {act} not expressible in ONNX")


def _t_batchnorm(ctx, name, ins, p):
    if int(p.get("axis", 1)) != 1:
        raise ValueError("ONNX BatchNormalization is channels-first (axis=1)")
    return [_node("BatchNormalization",
                  [ins[0], ins[1], ins[2], ins[3], ins[4]], [name], name,
                  epsilon=float(p.get("eps", 1e-3)),
                  momentum=float(p.get("momentum", 0.9)))]


def _t_pooling(ctx, name, ins, p):
    ptype = p.get("pool_type", "max")
    if p.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        return [_node(op, [ins[0]], [name], name)]
    kernel = _tuple_of(p.get("kernel"))
    nd = len(kernel)
    attrs = dict(kernel_shape=list(kernel),
                 strides=list(_tuple_of(p.get("stride") or 1, nd)),
                 pads=_pads2(_tuple_of(p.get("pad") or 0, nd)))
    if p.get("pooling_convention") == "full":
        attrs["ceil_mode"] = 1
    if ptype == "max":
        return [_node("MaxPool", [ins[0]], [name], name, **attrs)]
    if ptype == "avg":
        attrs["count_include_pad"] = int(p.get("count_include_pad", True))
        return [_node("AveragePool", [ins[0]], [name], name, **attrs)]
    raise ValueError(f"pool_type {ptype} not expressible in ONNX")


def _single_axis_softmax(ctx, op_type, name, inp, axis):
    """Emit opset-11 ``Softmax``/``LogSoftmax`` with true single-axis
    semantics. Opset 11 coerces to 2D — it normalizes over ALL dims from
    ``axis`` onward — which only matches mxnet's single-axis softmax when
    the axis is trailing (or the input is 2D with axis 1). For other cases
    transpose the axis to the end, apply, and transpose back."""
    shape = ctx.in_shapes[0] if ctx.in_shapes else None
    if shape is None:
        if axis in (-1,):
            return [_node(op_type, [inp], [name], name, axis=-1)]
        raise ValueError(
            f"ONNX export: {op_type} over axis={axis} needs a known input "
            f"rank to export conformantly at opset 11 (coerce-to-2D "
            f"semantics); shape inference failed for '{name}'")
    nd = len(shape)
    ax = axis % nd
    if ax == nd - 1:
        return [_node(op_type, [inp], [name], name, axis=ax)]
    perm = [i for i in range(nd) if i != ax] + [ax]
    inv = [perm.index(i) for i in range(nd)]
    t1, sm = f"{name}__pre", f"{name}__sm"
    return [
        _node("Transpose", [inp], [t1], t1, perm=perm),
        _node(op_type, [t1], [sm], sm, axis=nd - 1),
        _node("Transpose", [sm], [name], name, perm=inv),
    ]


def _t_softmax_output(ctx, name, ins, p):
    # reference _op_translations.py: SoftmaxOutput exports as plain Softmax
    # over the class axis (the loss head has no inference meaning)
    return _single_axis_softmax(ctx, "Softmax", name, ins[0], 1)


def _t_softmax(ctx, name, ins, p):
    return _single_axis_softmax(ctx, "Softmax", name, ins[0],
                                int(p.get("axis", -1)))


def _t_log_softmax(ctx, name, ins, p):
    return _single_axis_softmax(ctx, "LogSoftmax", name, ins[0],
                                int(p.get("axis", -1)))


def _t_flatten(ctx, name, ins, p):
    return [_node("Flatten", [ins[0]], [name], name, axis=1)]


def _t_reshape(ctx, name, ins, p):
    shape = ctx.const(np.asarray(p.get("shape"), np.int64), "shape")
    return [_node("Reshape", [ins[0], shape], [name], name)]


def _t_transpose(ctx, name, ins, p):
    return [_node("Transpose", [ins[0]], [name], name,
                  perm=list(p.get("axes") or []))]


def _t_concat(ctx, name, ins, p):
    return [_node("Concat", ins, [name], name, axis=int(p.get("dim", 1)))]


def _t_elemwise(op_type):
    def t(ctx, name, ins, p):
        return [_node(op_type, ins, [name], name)]
    return t


def _t_scalar(op_type):
    def t(ctx, name, ins, p):
        scalar = ctx.const(np.float32(p.get("scalar", 0.0)), "scalar")
        ins2 = [scalar, ins[0]] if p.get("reverse") else [ins[0], scalar]
        return [_node(op_type, ins2, [name], name)]
    return t


def _t_dropout(ctx, name, ins, p):
    return [_node("Dropout", [ins[0]], [name], name,
                  ratio=float(p.get("p", 0.5)))]


def _t_lrn(ctx, name, ins, p):
    return [_node("LRN", [ins[0]], [name], name,
                  alpha=float(p.get("alpha", 1e-4)),
                  beta=float(p.get("beta", 0.75)),
                  bias=float(p.get("knorm", 2.0)),
                  size=int(p.get("nsize")))]


def _t_embedding(ctx, name, ins, p):
    cast = name + "_idx"
    return [_node("Cast", [ins[0]], [cast], cast, to=ONNX_INT64),
            _node("Gather", [ins[1], cast], [name], name, axis=0)]


def _t_identity(ctx, name, ins, p):
    return [_node("Identity", [ins[0]], [name], name)]


def _t_space_to_depth(ctx, name, ins, p):
    return [_node("SpaceToDepth", [ins[0]], [name], name,
                  blocksize=int(p.get("block_size", 1)))]


def _t_depth_to_space(ctx, name, ins, p):
    return [_node("DepthToSpace", [ins[0]], [name], name,
                  blocksize=int(p.get("block_size", 1)))]


def _t_slice_channel(ctx, name, ins, p):
    n = int(p.get("num_outputs"))
    outs = [f"{name}_out{i}" for i in range(n)]
    return [_node("Split", [ins[0]], outs, name,
                  axis=int(p.get("axis", 1)))]


def _t_reduce(op_type):
    def t(ctx, name, ins, p):
        axis = p.get("axis")
        attrs = {"keepdims": int(p.get("keepdims", False))}
        if axis is not None:
            axis = [axis] if isinstance(axis, int) else list(axis)
            attrs["axes"] = axis
        return [_node(op_type, [ins[0]], [name], name, **attrs)]
    return t


def _t_dot(ctx, name, ins, p):
    if p.get("transpose_a") or p.get("transpose_b"):
        raise ValueError("dot with transpose flags not supported in export")
    return [_node("MatMul", ins, [name], name)]


def _t_clip(ctx, name, ins, p):
    lo = ctx.const(np.float32(p.get("a_min")), "min")
    hi = ctx.const(np.float32(p.get("a_max")), "max")
    return [_node("Clip", [ins[0], lo, hi], [name], name)]


def _t_pad(ctx, name, ins, p):
    mode = p.get("mode", "constant")
    pw = p.get("pad_width") or ()
    n = len(pw) // 2
    begins = [int(pw[2 * i]) for i in range(n)]
    ends = [int(pw[2 * i + 1]) for i in range(n)]
    pads = ctx.const(np.asarray(begins + ends, np.int64), "pads")
    return [_node("Pad", [ins[0], pads], [name], name,
                  mode={"constant": "constant", "edge": "edge",
                        "reflect": "reflect"}[mode])]


TRANSLATORS = {
    "Convolution": _t_convolution,
    "Deconvolution": _t_deconvolution,
    "FullyConnected": _t_fullyconnected,
    "Activation": _t_activation,
    "LeakyReLU": _t_leakyrelu,
    "BatchNorm": _t_batchnorm,
    "Pooling": _t_pooling,
    "SoftmaxOutput": _t_softmax_output,
    "softmax": _t_softmax,
    "log_softmax": _t_log_softmax,
    "SoftmaxActivation": _t_softmax_output,
    "Flatten": _t_flatten,
    "Reshape": _t_reshape,
    "transpose": _t_transpose,
    "Concat": _t_concat,
    "elemwise_add": _t_elemwise("Add"),
    "elemwise_sub": _t_elemwise("Sub"),
    "elemwise_mul": _t_elemwise("Mul"),
    "elemwise_div": _t_elemwise("Div"),
    "broadcast_add": _t_elemwise("Add"),
    "broadcast_sub": _t_elemwise("Sub"),
    "broadcast_mul": _t_elemwise("Mul"),
    "broadcast_div": _t_elemwise("Div"),
    "elemwise_add_scalar": _t_scalar("Add"),
    "elemwise_sub_scalar": _t_scalar("Sub"),
    "elemwise_mul_scalar": _t_scalar("Mul"),
    "elemwise_div_scalar": _t_scalar("Div"),
    "Dropout": _t_dropout,
    "LRN": _t_lrn,
    "Embedding": _t_embedding,
    "identity": _t_identity,
    "BlockGrad": _t_identity,
    "space_to_depth": _t_space_to_depth,
    "depth_to_space": _t_depth_to_space,
    "SliceChannel": _t_slice_channel,
    "sum": _t_reduce("ReduceSum"),
    "mean": _t_reduce("ReduceMean"),
    "max": _t_reduce("ReduceMax"),
    "min": _t_reduce("ReduceMin"),
    "dot": _t_dot,
    "clip": _t_clip,
    "pad": _t_pad,
    "relu": lambda ctx, name, ins, p: [_node("Relu", [ins[0]], [name], name)],
    "sigmoid": lambda ctx, name, ins, p: [_node("Sigmoid", [ins[0]], [name], name)],
    "tanh": lambda ctx, name, ins, p: [_node("Tanh", [ins[0]], [name], name)],
    "exp": lambda ctx, name, ins, p: [_node("Exp", [ins[0]], [name], name)],
    "log": lambda ctx, name, ins, p: [_node("Log", [ins[0]], [name], name)],
    "sqrt": lambda ctx, name, ins, p: [_node("Sqrt", [ins[0]], [name], name)],
    "abs": lambda ctx, name, ins, p: [_node("Abs", [ins[0]], [name], name)],
    "negative": lambda ctx, name, ins, p: [_node("Neg", [ins[0]], [name], name)],
}


def export_symbol(symbol, params, input_shapes, input_dtype=np.float32,
                  graph_name="mxnet_tpu_graph"):
    """Serialize a Symbol + {name: ndarray} params into ONNX ModelProto
    bytes. ``input_shapes`` is {input_name: shape} for the data inputs
    (everything in list_arguments() not found in params)."""
    from ...ndarray.ndarray import NDArray

    params = {k: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v))
              for k, v in params.items()}

    nodes_b = []
    ctx = _Ctx()
    name_of = {}  # (id(node), slot) -> ONNX value name
    used_names = set()

    def uniq(name):
        # gluon traces name every layer's op node "fwd"; ONNX value names
        # must be graph-unique
        if name not in used_names:
            used_names.add(name)
            return name
        k = 1
        while f"{name}_{k}" in used_names:
            k += 1
        used_names.add(f"{name}_{k}")
        return f"{name}_{k}"

    graph_nodes = symbol._topo_nodes()
    out_specs = symbol._outputs
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()

    data_inputs = [n for n in arg_names if n not in params]
    missing = [n for n in data_inputs if n not in input_shapes]
    if missing:
        raise ValueError(f"export: provide input_shapes for {missing}")

    # per-node value shapes: opset-11 coerce-to-2D ops (Softmax/LogSoftmax)
    # need input rank to stay spec-conformant on ndim>2 non-trailing axes
    shape_seed = dict(input_shapes)
    shape_seed.update({k: v.shape for k, v in params.items()})
    try:
        node_shapes = symbol._propagate_shapes(shape_seed)
    except Exception:  # export still works for rank-agnostic graphs
        node_shapes = {}

    for node in graph_nodes:
        if node.is_var:
            name_of[(id(node), 0)] = uniq(node.name)
            continue
        op = node.op
        t = TRANSLATORS.get(op)
        if t is None:
            raise ValueError(
                f"ONNX export: op '{op}' has no translator "
                f"(node '{node.name}'); supported: {sorted(TRANSLATORS)}")
        from ...ops.registry import get_op

        p = get_op(op).normalize(node.params)
        ins = [name_of[(id(i), s)] for i, s in node.inputs]
        node_name = uniq(node.name)
        ctx.in_shapes = [node_shapes.get((id(i), s)) for i, s in node.inputs]
        out_nodes = t(ctx, node_name, ins, p)
        nodes_b.extend(out_nodes)
        # register outputs: single-output default; Split declares its own
        if op == "SliceChannel":
            for i in range(int(p.get("num_outputs"))):
                name_of[(id(node), i)] = f"{node_name}_out{i}"
        else:
            name_of[(id(node), 0)] = node_name

    initializers = [_tensor(k, v) for k, v in params.items()
                    if k in set(arg_names) | set(aux_names)]
    initializers += ctx.extra_init
    inputs = [_value_info(n, input_shapes[n]) for n in data_inputs]
    outputs = [_value_info(name_of[(id(n), i)], ())
               for n, i in out_specs]
    graph = _graph(nodes_b, graph_name, initializers, inputs, outputs)
    return _model(graph)
