"""ONNX model -> Symbol graph deserialization.

Parity: python/mxnet/contrib/onnx/onnx2mx/import_onnx.py. Covers the op
set this framework's exporter emits (export_onnx.TRANSLATORS) so
export→import round-trips reproduce the original network; models produced
by other exporters work as long as they stay inside that op set.
"""
from __future__ import annotations

import numpy as np

from . import proto as P
from .export_onnx import ONNX_FLOAT, ONNX_INT64

# AttributeProto.type values
_AF, _AI, _AS, _AT, _AFS, _AIS, _ASS = 1, 2, 3, 4, 6, 7, 8


def _ints(field_vals):
    """Repeated int64 field: proto3 serializers pack the list into one
    LEN blob; our own emitter writes them unpacked. Accept both."""
    out = []
    for v in field_vals:
        if isinstance(v, (bytes, bytearray)):
            out.extend(P.parse_packed_ints(v))
        else:
            out.append(int(v))
    return out


def _floats(field_vals):
    out = []
    for v in field_vals:
        if isinstance(v, (bytes, bytearray)):
            out.extend(P.parse_packed_floats(v))
        else:
            out.append(float(v))
    return out


def _parse_tensor(raw):
    f = P.parse_message(raw)
    dims = _ints(f.get(1, []))
    dtype = P.first_int(f, 2, ONNX_FLOAT)
    name = P.first_str(f, 8)
    if 9 in f:  # raw_data
        buf = f[9][0]
        np_dtype = np.float32 if dtype == ONNX_FLOAT else np.int64
        arr = np.frombuffer(buf, dtype=np_dtype).reshape(dims)
    elif dtype == ONNX_FLOAT and 4 in f:
        arr = np.asarray(_floats(f[4]), np.float32).reshape(dims)
    elif dtype == ONNX_INT64 and 7 in f:
        arr = np.asarray(_ints(f[7]), np.int64).reshape(dims)
    else:
        arr = np.zeros(dims, np.float32)
    return name, arr


def _parse_attr(raw):
    f = P.parse_message(raw)
    name = P.first_str(f, 1)
    atype = P.first_int(f, 20)
    # proto3 omits zero-valued scalars from the wire: default them
    if atype == _AF:
        return name, float(f.get(2, [0.0])[0])
    if atype == _AI:
        return name, int(f.get(3, [0])[0])
    if atype == _AS:
        return name, f.get(4, [b""])[0].decode()
    if atype == _AFS:
        return name, _floats(f.get(7, []))
    if atype == _AIS:
        return name, _ints(f.get(8, []))
    if atype == _AT:
        return name, _parse_tensor(f[5][0])
    raise ValueError(f"attribute {name}: unsupported type {atype}")


def _parse_node(raw):
    f = P.parse_message(raw)
    return {
        "inputs": [v.decode() for v in f.get(1, [])],
        "outputs": [v.decode() for v in f.get(2, [])],
        "name": P.first_str(f, 3),
        "op": P.first_str(f, 4),
        "attrs": dict(_parse_attr(a) for a in f.get(5, [])),
    }


def parse_model(data: bytes):
    """ModelProto bytes -> dict with nodes/initializers/inputs/outputs."""
    mf = P.parse_message(data)
    graph = P.parse_message(P.first_bytes(mf, 7))
    nodes = [_parse_node(n) for n in graph.get(1, [])]
    inits = dict(_parse_tensor(t) for t in graph.get(5, []))

    def _vi_name(raw):
        return P.first_str(P.parse_message(raw), 1)

    inputs = [_vi_name(v) for v in graph.get(11, [])]
    outputs = [_vi_name(v) for v in graph.get(12, [])]
    opset = 0
    for os_raw in mf.get(14, []):
        osf = P.parse_message(os_raw)
        opset = max(opset, P.first_int(osf, 2))
    return {"nodes": nodes, "initializers": inits, "inputs": inputs,
            "outputs": outputs, "opset": opset,
            "producer": P.first_str(mf, 2)}


# ------------------------------------------------------- op constructors
#
# Each builder: fn(sym_mod, ins(list of Symbols/values), attrs, consts)
# -> Symbol (or list of Symbols for multi-output).

def _b_conv(sym, ins, a, consts):
    kernel = tuple(a["kernel_shape"])
    nd = len(kernel)
    pads = a.get("pads") or [0] * (2 * nd)
    begins, ends = pads[:nd], pads[nd:]
    pad = tuple((b, e) for b, e in zip(begins, ends))
    if all(b == e for b, e in pad):
        pad = tuple(b for b, _ in pad)
    nf = int(consts.shape_of(ins[1])[0])
    return sym.Convolution(*ins, kernel=kernel,
                           stride=tuple(a.get("strides") or (1,) * nd),
                           dilate=tuple(a.get("dilations") or (1,) * nd),
                           pad=pad, num_group=int(a.get("group", 1)),
                           num_filter=nf, no_bias=len(ins) < 3)


def _b_deconv(sym, ins, a, consts):
    kernel = tuple(a["kernel_shape"])
    nd = len(kernel)
    pads = a.get("pads") or [0] * (2 * nd)
    if pads[:nd] != pads[nd:]:
        raise ValueError(
            f"asymmetric ConvTranspose pads {pads} not supported on import")
    g = int(a.get("group", 1))
    nf = int(consts.shape_of(ins[1])[1]) * g
    return sym.Deconvolution(*ins, kernel=kernel,
                             stride=tuple(a.get("strides") or (1,) * nd),
                             dilate=tuple(a.get("dilations") or (1,) * nd),
                             pad=tuple(pads[:nd]),
                             num_group=g, num_filter=nf,
                             no_bias=len(ins) < 3)


def _b_gemm(sym, ins, a, consts):
    assert a.get("transB", 0) == 1 and a.get("transA", 0) == 0, \
        "only Gemm(transB=1) (the FullyConnected export form) supported"
    num_hidden = consts.shape_of(ins[1])[0]
    return sym.FullyConnected(ins[0], ins[1], ins[2],
                              num_hidden=int(num_hidden), flatten=False)


def _b_bn(sym, ins, a, consts):
    return sym.BatchNorm(ins[0], ins[1], ins[2], ins[3], ins[4],
                         eps=float(a.get("epsilon", 1e-5)),
                         momentum=float(a.get("momentum", 0.9)),
                         fix_gamma=False)


def _b_pool(op_type):
    def b(sym, ins, a, consts):
        if op_type in ("GlobalMaxPool", "GlobalAveragePool"):
            return sym.Pooling(
                ins[0], global_pool=True, kernel=(1, 1),
                pool_type="max" if "Max" in op_type else "avg")
        kernel = tuple(a["kernel_shape"])
        nd = len(kernel)
        pads = a.get("pads") or [0] * (2 * nd)
        if pads[:nd] != pads[nd:]:
            raise ValueError(
                f"asymmetric pooling pads {pads} not supported on import")
        kw = dict(kernel=kernel,
                  stride=tuple(a.get("strides") or (1,) * nd),
                  pad=tuple(pads[:nd]),
                  pool_type="max" if op_type == "MaxPool" else "avg")
        if a.get("ceil_mode"):
            kw["pooling_convention"] = "full"
        if op_type == "AveragePool":
            # ONNX spec default is 0 (exclude padding from the average)
            kw["count_include_pad"] = bool(a.get("count_include_pad", 0))
        return sym.Pooling(ins[0], **kw)
    return b


def _b_simple(mx_op, **fixed):
    def b(sym, ins, a, consts):
        return getattr(sym, mx_op)(*ins, **fixed)
    return b


def _b_softmax(mx_op):
    def b(sym, ins, a, consts):
        return getattr(sym, mx_op)(ins[0], axis=int(a.get("axis", -1)))
    return b


def _b_reshape(sym, ins, a, consts):
    shape = consts.value_of(ins[1])
    return sym.Reshape(ins[0], shape=tuple(int(v) for v in shape))


def _b_transpose(sym, ins, a, consts):
    return sym.transpose(ins[0], axes=tuple(a.get("perm") or ()))


def _b_concat(sym, ins, a, consts):
    return sym.Concat(*ins, dim=int(a.get("axis", 1)))


def _b_clip(sym, ins, a, consts):
    lo = float(consts.value_of(ins[1])) if len(ins) > 1 else float(a["min"])
    hi = float(consts.value_of(ins[2])) if len(ins) > 2 else float(a["max"])
    return sym.clip(ins[0], a_min=lo, a_max=hi)


def _b_pad(sym, ins, a, consts):
    pads = [int(v) for v in consts.value_of(ins[1])]
    n = len(pads) // 2
    pw = []
    for i in range(n):
        pw += [pads[i], pads[n + i]]
    return sym.pad(ins[0], mode=a.get("mode", "constant"),
                   pad_width=tuple(pw))


def _b_dropout(sym, ins, a, consts):
    return sym.Dropout(ins[0], p=float(a.get("ratio", 0.5)))


def _b_lrn(sym, ins, a, consts):
    return sym.LRN(ins[0], alpha=float(a.get("alpha", 1e-4)),
                   beta=float(a.get("beta", 0.75)),
                   knorm=float(a.get("bias", 2.0)),
                   nsize=int(a["size"]))


def _b_gather(sym, ins, a, consts):
    # exporter form: Gather(weight, Cast(idx)) == Embedding
    w_shape = consts.shape_of(ins[0])
    return sym.Embedding(ins[1], ins[0], input_dim=int(w_shape[0]),
                         output_dim=int(w_shape[1]))


def _b_cast(sym, ins, a, consts):
    to = int(a.get("to", ONNX_FLOAT))
    return sym.Cast(ins[0],
                    dtype="int64" if to == ONNX_INT64 else "float32")


def _b_split(sym, ins, a, consts):
    nout = len(a["__outputs__"])
    return sym.SliceChannel(ins[0], num_outputs=nout,
                            axis=int(a.get("axis", 1)))


def _b_reduce(mx_op):
    def b(sym, ins, a, consts):
        axes = a.get("axes")
        kw = {"keepdims": bool(a.get("keepdims", 1))}
        if axes is not None:
            kw["axis"] = tuple(axes) if len(axes) > 1 else int(axes[0])
        return getattr(sym, mx_op)(ins[0], **kw)
    return b


def _b_s2d(mx_op):
    def b(sym, ins, a, consts):
        return getattr(sym, mx_op)(ins[0], block_size=int(a["blocksize"]))
    return b


def _b_leaky(sym, ins, a, consts):
    return sym.LeakyReLU(ins[0], act_type="leaky",
                         slope=float(a.get("alpha", 0.01)))


def _b_elu(sym, ins, a, consts):
    return sym.LeakyReLU(ins[0], act_type="elu",
                         slope=float(a.get("alpha", 1.0)))


BUILDERS = {
    "Conv": _b_conv,
    "ConvTranspose": _b_deconv,
    "Gemm": _b_gemm,
    "BatchNormalization": _b_bn,
    "MaxPool": _b_pool("MaxPool"),
    "AveragePool": _b_pool("AveragePool"),
    "GlobalMaxPool": _b_pool("GlobalMaxPool"),
    "GlobalAveragePool": _b_pool("GlobalAveragePool"),
    "Relu": _b_simple("relu"),
    "Sigmoid": _b_simple("sigmoid"),
    "Tanh": _b_simple("tanh"),
    "Softplus": lambda sym, ins, a, c: sym.Activation(ins[0], act_type="softrelu"),
    "Softsign": _b_simple("softsign"),
    "LeakyRelu": _b_leaky,
    "Elu": _b_elu,
    "Selu": lambda sym, ins, a, c: sym.LeakyReLU(ins[0], act_type="selu"),
    "PRelu": lambda sym, ins, a, c: sym.LeakyReLU(ins[0], ins[1], act_type="prelu"),
    "Softmax": _b_softmax("softmax"),
    "LogSoftmax": _b_softmax("log_softmax"),
    "Flatten": _b_simple("Flatten"),
    "Reshape": _b_reshape,
    "Transpose": _b_transpose,
    "Concat": _b_concat,
    "Add": _b_simple("broadcast_add"),
    "Sub": _b_simple("broadcast_sub"),
    "Mul": _b_simple("broadcast_mul"),
    "Div": _b_simple("broadcast_div"),
    "Sum": _b_simple("add_n"),
    "MatMul": _b_simple("dot"),
    "Dropout": _b_dropout,
    "LRN": _b_lrn,
    "Gather": _b_gather,
    "Cast": _b_cast,
    "Identity": _b_simple("identity"),
    "SpaceToDepth": _b_s2d("space_to_depth"),
    "DepthToSpace": _b_s2d("depth_to_space"),
    "Split": _b_split,
    "ReduceSum": _b_reduce("sum"),
    "ReduceMean": _b_reduce("mean"),
    "ReduceMax": _b_reduce("max"),
    "ReduceMin": _b_reduce("min"),
    "Clip": _b_clip,
    "Pad": _b_pad,
    "Exp": _b_simple("exp"),
    "Log": _b_simple("log"),
    "Sqrt": _b_simple("sqrt"),
    "Abs": _b_simple("abs"),
    "Neg": _b_simple("negative"),
}


def build_symbol(model):
    """Parsed model dict -> (Symbol, arg_params, aux_params)."""
    import mxnet_tpu.symbol as S
    import mxnet_tpu.ndarray as nd

    inits = model["initializers"]
    values = {}          # ONNX value name -> Symbol
    consumed_consts = set()

    for name in model["inputs"]:
        if name not in inits:
            values[name] = S.Variable(name)
    for name in inits:
        values[name] = S.Variable(name)

    class _C:
        """Constant lookup by Symbol: only initializer variables can be
        constants, and those are all created above — index them once."""

        def __init__(self):
            self._sym_names = {id(values[n]): n for n in inits}

        def value_of(self, x):
            name = self._sym_names.get(id(x), x)
            return inits[name]

        def shape_of(self, x):
            return self.value_of(x).shape

    consts_lookup = _C()
    for node in model["nodes"]:
        b = BUILDERS.get(node["op"])
        if b is None:
            raise ValueError(f"ONNX import: unsupported op {node['op']}")
        ins = []
        for i in node["inputs"]:
            v = values.get(i)
            if v is None:
                raise ValueError(f"ONNX import: undefined input '{i}'")
            ins.append(v)
        attrs = dict(node["attrs"])
        attrs["__outputs__"] = node["outputs"]
        out = b(S, ins, attrs, consts_lookup)
        if node["op"] == "Split":
            outs = [out[i] for i in range(len(node["outputs"]))]
        else:
            outs = out if isinstance(out, (list, tuple)) else [out]
        for oname, osym in zip(node["outputs"], outs):
            values[oname] = osym
        # constants consumed structurally (Reshape shape, Clip bounds, pads)
        if node["op"] in ("Reshape", "Clip", "Pad"):
            for i in node["inputs"][1:]:
                consumed_consts.add(i)

    out_syms = [values[o] for o in model["outputs"]]
    out = out_syms[0] if len(out_syms) == 1 else S.Group(out_syms)

    arg_names = set(out.list_arguments())
    aux_names = set(out.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name, arr in inits.items():
        if name in consumed_consts:
            continue
        target = aux_params if (name in aux_names or
                                "moving_" in name or "running_" in name) \
            else arg_params
        if name in arg_names or name in aux_names:
            target[name] = nd.array(np.asarray(arr, np.float32))
    return out, arg_params, aux_params
