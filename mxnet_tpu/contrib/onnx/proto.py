"""Self-contained protobuf wire codec for the ONNX schema subset.

The environment ships no ``onnx`` package (and none is needed at runtime on
TPU), so serialization is done directly against the protobuf wire format
(proto3). Only the message fields the exporter/importer use are modeled —
see the ONNX spec (onnx/onnx.proto) for field numbers.

Messages are represented as plain dicts; repeated fields as lists. The
encoder/decoder pair is exercised by the round-trip tests in
tests/test_onnx.py.
"""
from __future__ import annotations

import struct

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _varint(n: int) -> bytes:
    if n < 0:  # proto int64: 10-byte two's complement
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            # interpret as signed int64
            if result >= 1 << 63:
                result -= 1 << 64
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def emit_int(field: int, v: int) -> bytes:
    return _tag(field, _VARINT) + _varint(int(v))


def emit_float(field: int, v: float) -> bytes:
    return _tag(field, _I32) + struct.pack("<f", float(v))


def emit_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, _LEN) + _varint(len(payload)) + payload


def emit_str(field: int, s: str) -> bytes:
    return emit_bytes(field, s.encode("utf-8"))


def emit_packed_ints(field: int, vals) -> bytes:
    payload = b"".join(_varint(int(v)) for v in vals)
    return emit_bytes(field, payload)


def emit_packed_floats(field: int, vals) -> bytes:
    payload = b"".join(struct.pack("<f", float(v)) for v in vals)
    return emit_bytes(field, payload)


def parse_message(buf: bytes):
    """Decode a message into {field_number: [raw values]} where varints come
    back as ints and length-delimited fields as bytes (caller interprets
    nested messages / strings / packed arrays)."""
    fields: dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            v, pos = _read_varint(buf, pos)
        elif wire == _LEN:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == _I32:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == _I64:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:  # pragma: no cover - malformed input
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def parse_packed_ints(raw: bytes):
    vals, pos = [], 0
    while pos < len(raw):
        v, pos = _read_varint(raw, pos)
        vals.append(v)
    return vals


def parse_packed_floats(raw: bytes):
    return list(struct.unpack(f"<{len(raw) // 4}f", raw))


def first_int(fields, num, default=0):
    v = fields.get(num)
    return int(v[0]) if v else default


def first_bytes(fields, num, default=b""):
    v = fields.get(num)
    return v[0] if v else default


def first_str(fields, num, default=""):
    v = fields.get(num)
    return v[0].decode("utf-8") if v else default
