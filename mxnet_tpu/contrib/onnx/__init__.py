"""ONNX export/import (parity: python/mxnet/contrib/onnx/).

Reference surface: mx2onnx.export_model (export_model.py:35) and
onnx2mx.import_model. The environment ships no onnx package, so the
ModelProto is written/read by the self-contained wire codec in proto.py;
round-trip fidelity is proven by tests/test_onnx.py (forward equivalence
after export→import).
"""
from __future__ import annotations

import numpy as np

from .export_onnx import export_symbol, TRANSLATORS, OPSET
from .import_onnx import parse_model, build_symbol, BUILDERS

__all__ = ["export_model", "import_model", "get_model_metadata",
           "export_symbol", "parse_model"]


def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol (or saved json path) + params (dict or .params
    path) to an ONNX file (reference export_model.py:35)."""
    from ...symbol import load as sym_load
    from ... import ndarray as nd

    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(params, str):
        loaded = nd.load(params)
        params = {}
        for k, v in loaded.items():
            params[k.split(":", 1)[-1]] = v
    if isinstance(input_shape, tuple):
        input_shape = [input_shape]
    data_names = [n for n in sym.list_arguments() if n not in params]
    input_shapes = dict(zip(data_names, input_shape))
    blob = export_symbol(sym, params, input_shapes)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    if verbose:
        print(f"ONNX model saved to {onnx_file_path} "
              f"({len(blob)} bytes, opset {OPSET})")
    return onnx_file_path


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params)
    (reference onnx2mx/import_model.py)."""
    with open(model_file, "rb") as f:
        model = parse_model(f.read())
    return build_symbol(model)


def get_model_metadata(model_file):
    """Input/output names of an ONNX model
    (reference onnx2mx/import_model.py get_model_metadata)."""
    with open(model_file, "rb") as f:
        model = parse_model(f.read())
    return {"input_tensor_data": model["inputs"],
            "output_tensor_data": model["outputs"],
            "producer": model["producer"], "opset": model["opset"]}
