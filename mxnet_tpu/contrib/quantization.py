"""INT8 model quantization driver.

Capability parity with python/mxnet/contrib/quantization.py
(quantize_model: graph pass inserting quantize/dequantize around
FullyConnected/Convolution + naive min/max calibration over a data set).
TPU-native form: the pass produces a *fake-quant* graph — fp32 values are
rounded through the int8 grid of ops/quantization.py at every quantized
boundary — which reproduces the reference's int8 accuracy exactly while
staying one XLA program; int8 kernels can replace the boundaries later
without changing this surface.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_graph"]

_QUANTIZABLE = ("FullyConnected", "Convolution")


def quantize_graph(sym, excluded_sym_names=(), quantized_dtype="int8",
                   calib_ranges=None):
    """Clone `sym` with fake-quant (quantize_v2 -> dequantize) inserted on
    the data and weight inputs of every quantizable node.

    calib_ranges: optional {(producer_name, slot): (min, max)} from
    calibration; quantize_v2 nodes without a range compute min/max at
    runtime (the reference's non-calibrated mode).
    """
    from ..symbol.symbol import Symbol, _Node

    excluded = set(excluded_sym_names)
    mapping = {}

    def cloned(node):
        if id(node) in mapping:
            return mapping[id(node)]
        new = _Node(node.op, node.name, params=dict(node.params),
                    attrs=dict(node.attrs))
        new.aux_mark = node.aux_mark
        mapping[id(node)] = new
        new.inputs = [(cloned(n), s) for n, s in node.inputs]
        if node.op in _QUANTIZABLE and node.name not in excluded:
            # wrap data (slot 0) and weight (slot 1) in fake-quant pairs
            for i in range(min(2, len(new.inputs))):
                src_node, src_slot = new.inputs[i]
                params = {"out_type": quantized_dtype}
                key = (src_node.name, src_slot)
                if calib_ranges and key in calib_ranges:
                    lo, hi = calib_ranges[key]
                    params["min_calib_range"] = float(lo)
                    params["max_calib_range"] = float(hi)
                q = _Node("_contrib_quantize_v2",
                          f"{node.name}_in{i}_quantize", params=params,
                          inputs=[(src_node, src_slot)])
                dq = _Node("_contrib_dequantize",
                           f"{node.name}_in{i}_dequantize",
                           inputs=[(q, 0), (q, 1), (q, 2)])
                new.inputs[i] = (dq, 0)
        return new

    outputs = [(cloned(n), s) for n, s in sym._outputs]
    return Symbol(outputs)


def _collect_ranges(sym, arg_params, aux_params, data_names, label_names,
                    calib_data, num_calib_examples, logger=None):
    """Naive calibration: run the fp32 graph over calib batches recording
    per-producer min/max (contrib/quantization.py _LayerOutputCollector)."""
    from .. import context as ctx_mod
    from ..executor import Executor  # noqa: F401  (bind path)

    targets = set()
    for node in sym._topo_nodes():
        if node.op in _QUANTIZABLE:
            for n, s in node.inputs[:2]:
                targets.add((n.name, s))

    ranges = {}
    # executor monitor names outputs "<node>_output[<i>]"
    name_of = {}
    for node_name, slot in targets:
        mon = (f"{node_name}_output" if slot == 0
               else f"{node_name}_output{slot}")
        name_of[mon] = (node_name, slot)

    def tap(mon_name, arr):
        key = name_of.get(mon_name)
        if key is None:
            return
        a = arr.asnumpy()
        lo, hi = float(a.min()), float(a.max())
        cur = ranges.get(key)
        ranges[key] = ((lo, hi) if cur is None
                       else (min(cur[0], lo), max(cur[1], hi)))

    # range of weights/vars straight from params
    for (name, slot) in targets:
        if name in arg_params:
            a = arg_params[name].asnumpy()
            ranges[(name, slot)] = (float(a.min()), float(a.max()))

    def _expand(key, a):
        lo, hi = ranges.get(key, (np.inf, -np.inf))
        ranges[key] = (min(lo, float(a.min())), max(hi, float(a.max())))

    seen = 0
    ex = None
    calib_data.reset()
    for batch in calib_data:
        args = dict(arg_params)
        for n, d in zip(data_names, batch.data):
            args[n] = d
            _expand((n, 0), d.asnumpy())
        for ln in label_names or ():
            if ln in sym.list_arguments() and ln not in args:
                from ..ndarray import ndarray as _nd

                args[ln] = _nd.zeros((batch.data[0].shape[0],))
        if ex is None:  # bind once; later batches just feed new inputs
            ex = sym.bind(ctx_mod.current_context(), args,
                          aux_states=dict(aux_params) if aux_params
                          else None)
            ex.set_monitor_callback(tap, monitor_all=True)
            ex.forward(is_train=False)
        else:
            ex.forward(is_train=False,
                       **{n: d for n, d in zip(data_names, batch.data)})
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), excluded_sym_names=(),
                   calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   logger=None):
    """Quantize a symbolic model (contrib/quantization.py:quantize_model).

    calib_mode: 'none' (runtime min/max) or 'naive' (min/max collected
    over calib_data; the reference's entropy mode is descoped — naive
    calibration differs <0.2% mAP in the reference's own SSD table).
    Returns (quantized_symbol, arg_params, aux_params).
    """
    if quantized_dtype not in ("int8", "uint8"):
        raise MXNetError("quantized_dtype must be int8 or uint8")
    ranges = None
    if calib_mode == "naive":
        if calib_data is None:
            raise MXNetError("calib_mode='naive' requires calib_data")
        ranges = _collect_ranges(sym, arg_params, aux_params, data_names,
                                 label_names, calib_data,
                                 num_calib_examples, logger)
    elif calib_mode != "none":
        raise MXNetError(f"unsupported calib_mode {calib_mode!r} "
                         "(supported: 'none', 'naive')")
    qsym = quantize_graph(sym, excluded_sym_names, quantized_dtype, ranges)
    return qsym, arg_params, aux_params
