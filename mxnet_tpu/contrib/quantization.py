"""INT8 model quantization driver.

Capability parity with python/mxnet/contrib/quantization.py
(quantize_model graph pass + calibration) and
src/operator/quantization/calibrate.cc (entropy/KL threshold search).

Two graph modes:
- quantize_mode='fake' — fp32 values rounded through the int8 grid at
  every quantized boundary (accuracy flow; one XLA program).
- quantize_mode='full' — FullyConnected/Convolution replaced by REAL
  int8 kernels (ops/quantization.py quantized_* — int8 operands, int32
  MXU accumulation), quantize/dequantize at the boundaries. Requires
  calibrated ranges (calib_mode 'naive' or 'entropy').

Calibration modes: 'none' (runtime min/max), 'naive' (min/max over a
calibration set), 'entropy' (KL-divergence-optimal clip threshold over
activation histograms — calibrate.cc).

Calibration is a product step (docs/quantization.md): :func:`calibrate`
returns a :class:`CalibrationTable` (per-tensor thresholds + calib mode
+ sample count) that serving hosts ship next to the params file, so a
`Predictor` quantizes WITHOUT calibration data; applying a table to a
model it was not calibrated for raises :class:`CalibrationMismatchError`
instead of silently serving mis-scaled answers. Collectors accumulate
min/max and |activation| histograms ON DEVICE and pull one small result
per monitored tensor per batch (not one full-tensor transfer per
histogram), timed by the ``calib_*`` counters in
``profiler.dispatch_stats()``.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import time

import numpy as np

from ..base import MXNetError
from ..resilience import faults as _faults

__all__ = ["quantize_model", "quantize_graph", "fold_batch_norm",
           "calibrate", "CalibrationTable", "CalibrationMismatchError",
           "symbol_digest", "stats", "reset_stats"]

# Calibration observability (merged into profiler.dispatch_stats()).
_STATS = {
    "calib_batches": 0,       # calibration batches fed through the graph
    "calib_tensor_syncs": 0,  # device->host pulls (one per monitored
                              # tensor per batch: a scalar pair or a
                              # histogram, never the full activation)
    "calib_ms": 0,            # cumulative wall-clock ms in the collectors
    "calib_tables_saved": 0,  # CalibrationTable.save() calls
    "calib_tables_loaded": 0, # CalibrationTable.load() calls
    "calib_mismatches": 0,    # stale table/model pairs rejected
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


@contextlib.contextmanager
def _calib_timer():
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _STATS["calib_ms"] += int((time.perf_counter() - t0) * 1e3)


def _calib_bins(num_bins=None):
    if num_bins is not None:
        return int(num_bins)
    v = os.environ.get("MXNET_TPU_INT8_CALIB_BINS", "").strip()
    return int(v) if v else 2048


_QUANTIZABLE = ("FullyConnected", "Convolution")


_FULL_OPS = {"FullyConnected": "_contrib_quantized_fully_connected",
             "Convolution": "_contrib_quantized_conv"}
_FULL_PARAMS = {
    "FullyConnected": ("num_hidden", "no_bias", "flatten"),
    "Convolution": ("kernel", "stride", "dilate", "pad", "num_filter",
                    "num_group", "no_bias", "layout"),
}


def quantize_graph(sym, excluded_sym_names=(), quantized_dtype="int8",
                   calib_ranges=None, quantize_mode="fake",
                   offline_params=None, offline_out=None):
    """Clone `sym` with int8 boundaries on every quantizable node.

    quantize_mode='fake': quantize_v2 -> dequantize pairs on data/weight
    inputs (values ride the int8 grid, compute stays fp32).
    quantize_mode='full': the node itself becomes the int8 kernel
    (quantized_fully_connected / quantized_conv, int32 accumulation)
    followed by dequantize — requires calib_ranges for the data input.

    calib_ranges: optional {(producer_name, slot): (min, max)} from
    calibration; quantize_v2 nodes without a range compute min/max at
    runtime (the reference's non-calibrated mode).

    offline_params: {var_name: numpy array} — in full mode, weight/bias
    variables in this dict are quantized OFFLINE (the reference's
    quantize-params step): their quantize nodes become plain
    '<name>_int8'/'_int8_min'/'_int8_max' variables whose values are
    written into `offline_out`, so inference never re-quantizes weights.
    """
    from ..symbol.symbol import Symbol, _Node, Variable

    if quantize_mode not in ("fake", "full"):
        raise MXNetError(f"quantize_mode must be fake|full, "
                         f"got {quantize_mode!r}")
    excluded = set(excluded_sym_names)
    mapping = {}
    offline_params = offline_params or {}

    def make_quant(name, src, dtype="int8", key=None):
        params = {"out_type": dtype}
        if calib_ranges and key in calib_ranges:
            lo, hi = calib_ranges[key]
            params["min_calib_range"] = float(lo)
            params["max_calib_range"] = float(hi)
        return _Node("_contrib_quantize_v2", name, params=params,
                     inputs=[src])

    def make_offline(var_name, key):
        """Quantize a parameter now (symmetric int8, same math as
        quantize_v2) and emit variables carrying the results."""
        a = np.asarray(offline_params[var_name], np.float32)
        if calib_ranges and key in calib_ranges:
            lo, hi = calib_ranges[key]
        else:
            lo, hi = float(a.min()), float(a.max())
        real = max(abs(lo), abs(hi), 1e-20)
        q = np.clip(np.round(a * (127.0 / real)), -127, 127) \
            .astype(np.int8)
        base = f"{var_name}_int8"
        if offline_out is not None:
            offline_out[base] = q
            offline_out[base + "_min"] = np.float32(-real)
            offline_out[base + "_max"] = np.float32(real)
        nodes = [Variable(base)._outputs[0][0],
                 Variable(base + "_min")._outputs[0][0],
                 Variable(base + "_max")._outputs[0][0]]
        # mimic a quantize node's (values, min, max) output triple

        class _Triple:
            pass

        t = _Triple()
        t.slots = [(nodes[0], 0), (nodes[1], 0), (nodes[2], 0)]
        return t

    def cloned(node):
        if id(node) in mapping:
            return mapping[id(node)]
        new = _Node(node.op, node.name, params=dict(node.params),
                    attrs=dict(node.attrs))
        new.aux_mark = node.aux_mark
        mapping[id(node)] = new
        new.inputs = [(cloned(n), s) for n, s in node.inputs]
        if node.op not in _QUANTIZABLE or node.name in excluded:
            return new
        if quantize_mode == "full":
            # replace with the real int8 kernel + boundary dequantize.
            # Range keys use the ORIGINAL producer name — a chained
            # quantizable producer's clone is its '<name>_dequantize'
            # node, which calibration never saw.
            qslots = []  # per input: [(node, slot) x3] = values/min/max
            for i, ((src_node, src_slot), (orig_src, orig_slot)) in \
                    enumerate(zip(new.inputs[:3], node.inputs[:3])):
                key = (orig_src.name, orig_slot)
                if orig_src.is_var and orig_src.name in offline_params:
                    qslots.append(make_offline(orig_src.name, key).slots)
                else:
                    q = make_quant(f"{node.name}_in{i}_quantize",
                                   (src_node, src_slot), quantized_dtype,
                                   key=key)
                    qslots.append([(q, 0), (q, 1), (q, 2)])
            d, w = qslots[0], qslots[1]
            b = qslots[2] if len(qslots) > 2 else qslots[1]
            inputs = [d[0], w[0], b[0], d[1], d[2], w[1], w[2], b[1], b[2]]
            qparams = {k: node.params[k]
                       for k in _FULL_PARAMS[node.op]
                       if k in node.params}
            if len(qslots) <= 2:
                qparams["no_bias"] = True
            qnode = _Node(_FULL_OPS[node.op], f"{node.name}_int8",
                          params=qparams, inputs=inputs)
            dq = _Node("_contrib_dequantize", f"{node.name}_dequantize",
                       inputs=[(qnode, 0), (qnode, 1), (qnode, 2)])
            # downstream consumers see this dequantized fp32 value
            mapping[id(node)] = dq
            return dq
        # fake-quant: wrap data (slot 0) and weight (slot 1)
        for i in range(min(2, len(new.inputs))):
            src_node, src_slot = new.inputs[i]
            orig_src, orig_slot = node.inputs[i]
            q = make_quant(f"{node.name}_in{i}_quantize",
                           (src_node, src_slot), quantized_dtype,
                           key=(orig_src.name, orig_slot))
            dq = _Node("_contrib_dequantize",
                       f"{node.name}_in{i}_dequantize",
                       inputs=[(q, 0), (q, 1), (q, 2)])
            new.inputs[i] = (dq, 0)
        return new

    outputs = [(cloned(n), s) for n, s in sym._outputs]
    return Symbol(outputs)


def _quant_targets(sym):
    """(producer_name, slot) keys needing ranges: data, weight, and (for
    the full-int8 kernels) bias inputs of every quantizable node."""
    targets = set()
    for node in sym._topo_nodes():
        if node.op in _QUANTIZABLE:
            for n, s in node.inputs[:3]:
                targets.add((n.name, s))
    return targets


def _monitor_names(targets):
    """Executor monitor names outputs "<node>_output[<i>]"."""
    return {(f"{name}_output" if slot == 0 else f"{name}_output{slot}"):
            (name, slot) for name, slot in targets}


def _calibration_forward(sym, arg_params, aux_params, data_names,
                         label_names, calib_data, num_calib_examples,
                         tap, on_batch=None):
    """Shared calibration loop: bind once with a monitor callback, feed
    each calib batch (labels synthesized as zeros), honor the example
    cutoff. `tap(mon_name, arr)` observes every node output; `on_batch`
    observes the raw input batch. Returns the number of examples seen."""
    from .. import context as ctx_mod

    seen = 0
    ex = None
    calib_data.reset()
    for batch in calib_data:
        if on_batch is not None:
            on_batch(batch)
        if ex is None:  # bind once; later batches just feed new inputs
            args = dict(arg_params)
            for n, d in zip(data_names, batch.data):
                args[n] = d
            for ln in label_names or ():
                if ln in sym.list_arguments() and ln not in args:
                    from ..ndarray import ndarray as _nd

                    args[ln] = _nd.zeros((batch.data[0].shape[0],))
            ex = sym.bind(ctx_mod.current_context(), args,
                          aux_states=dict(aux_params) if aux_params
                          else None)
            ex.set_monitor_callback(tap, monitor_all=True)
            ex.forward(is_train=False)
        else:
            ex.forward(is_train=False,
                       **{n: d for n, d in zip(data_names, batch.data)})
        seen += batch.data[0].shape[0]
        _STATS["calib_batches"] += 1
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return seen


def _observed(arr):
    """Concrete array of one observed tensor: NDArrays resolve through
    ``_force()`` (a lazy bulk-segment placeholder must be flushed before
    device math can see it), raw arrays pass through."""
    if hasattr(arr, "_force"):
        return arr._force()
    return arr._data if hasattr(arr, "_data") else arr


def _device_minmax(arr):
    """(min, max) of one observed tensor with ONE small device->host
    pull: the reduction runs on device and only the scalar pair crosses
    the tunnel — never the full activation."""
    import jax.numpy as jnp

    a = _observed(arr)
    if isinstance(a, np.ndarray):
        _STATS["calib_tensor_syncs"] += 1
        return float(a.min()), float(a.max())
    pair = np.asarray(jnp.stack([jnp.min(a), jnp.max(a)]))
    _STATS["calib_tensor_syncs"] += 1
    return float(pair[0]), float(pair[1])


def _device_abs_hist(arr, hi, num_bins):
    """|activation| histogram of one observed tensor, accumulated on
    device; only the ``num_bins`` counts cross to the host (one sync per
    monitored tensor per batch — the eager-replay calibration cost fix
    from PERF.md round 5)."""
    import jax.numpy as jnp

    a = _observed(arr)
    if isinstance(a, np.ndarray):
        _STATS["calib_tensor_syncs"] += 1
        return np.histogram(np.abs(a).ravel(), bins=num_bins,
                            range=(0.0, hi))[0].astype(np.int64)
    counts, _edges = jnp.histogram(jnp.abs(a).ravel(), bins=num_bins,
                                   range=(0.0, hi))
    _STATS["calib_tensor_syncs"] += 1
    return np.asarray(counts).astype(np.int64)


def _collect_ranges(sym, arg_params, aux_params, data_names, label_names,
                    calib_data, num_calib_examples, logger=None,
                    seen_out=None):
    """Naive calibration: run the fp32 graph over calib batches recording
    per-producer min/max (contrib/quantization.py _LayerOutputCollector).
    Reductions run on device; only scalar pairs cross to the host.
    ``seen_out`` (a list) receives the example count when given."""
    targets = _quant_targets(sym)
    name_of = _monitor_names(targets)
    ranges = {}

    def _expand(key, pair):
        lo, hi = ranges.get(key, (np.inf, -np.inf))
        ranges[key] = (min(lo, pair[0]), max(hi, pair[1]))

    def tap(mon_name, arr):
        key = name_of.get(mon_name)
        if key is not None:
            _expand(key, _device_minmax(arr))

    # range of weights/vars straight from params
    for (name, slot) in targets:
        if name in arg_params:
            a = arg_params[name].asnumpy()
            ranges[(name, slot)] = (float(a.min()), float(a.max()))

    def on_batch(batch):
        for n, d in zip(data_names, batch.data):
            _expand((n, 0), _device_minmax(d))

    with _calib_timer():
        seen = _calibration_forward(sym, arg_params, aux_params,
                                    data_names, label_names, calib_data,
                                    num_calib_examples, tap, on_batch)
    if seen_out is not None:
        seen_out.append(seen)
    return ranges


def _entropy_threshold(hist, edges, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold over an |activation| histogram
    (src/operator/quantization/calibrate.cc ComputeEntropy; same algorithm
    as TensorRT's calibrator). Returns the threshold value."""
    nbins = len(hist)
    half = (num_quantized_bins + 1) // 2
    if nbins <= half:
        return float(edges[-1])
    hist = hist.astype(np.float64)

    def smooth(d, eps=1e-4):
        # calibrate.cc SmoothDistribution: move eps into empty bins so the
        # KL penalty for mass the candidate cannot represent is counted
        # instead of masked away
        is_zero = d == 0
        n_zero = int(is_zero.sum())
        n_nonzero = d.size - n_zero
        if n_nonzero == 0:
            return None
        if n_zero == 0:
            return d
        eps1 = eps * n_zero / n_nonzero
        if eps1 >= 1.0:
            return None
        out = d.copy()
        out[is_zero] = eps
        out[~is_zero] -= eps1
        return out

    best_kl, best_i = np.inf, nbins
    for i in range(half, nbins + 1):
        # reference distribution: clip everything beyond bin i into bin i-1
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()
        is_nonzero = hist[:i] > 0
        # candidate: quantize the first i bins into `half` levels, then
        # expand back over the nonzero support
        q = np.zeros(i, np.float64)
        group = i / half
        for j in range(half):
            lo = int(np.floor(j * group))
            hi = int(np.floor((j + 1) * group)) if j < half - 1 else i
            seg = slice(lo, max(hi, lo + 1))
            total = hist[seg].sum()
            nz = is_nonzero[seg].sum()
            if nz:
                q[seg] = np.where(is_nonzero[seg], total / nz, 0.0)
        # smooth the raw COUNT distributions (calibrate.cc order: counts
        # are >= 1 wherever nonzero, so eps never drives a bin negative),
        # normalize afterwards
        p = smooth(p)
        q = smooth(q)
        if p is None or q is None:
            continue
        p /= p.sum()
        q /= q.sum()
        mask = p > 0
        kl = float(np.sum(p[mask] * np.log(p[mask] / q[mask])))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return float(edges[best_i])


def _collect_entropy_ranges(sym, arg_params, aux_params, data_names,
                            label_names, calib_data, num_calib_examples,
                            num_bins=None, logger=None, seen_out=None):
    """Two passes: (1) max|activation| per target via the naive collector,
    (2) |activation| histograms, then the KL threshold per target.
    Weight/bias params keep exact min/max (the reference also only
    entropy-calibrates activations). Histograms accumulate ON DEVICE —
    each monitored tensor costs one ``num_bins``-count pull per batch,
    not a full-activation transfer per histogram (PERF.md round 5's
    eager-replay calibration cost)."""
    num_bins = _calib_bins(num_bins)
    naive = _collect_ranges(sym, arg_params, aux_params, data_names,
                            label_names, calib_data, num_calib_examples,
                            logger, seen_out=seen_out)
    param_keys = {k for k in naive if k[0] in arg_params}
    act_keys = [k for k in naive if k not in param_keys]
    max_abs = {k: max(abs(naive[k][0]), abs(naive[k][1]), 1e-20)
               for k in act_keys}
    hists = {k: np.zeros(num_bins, np.int64) for k in act_keys}
    name_of = _monitor_names(act_keys)

    def add_hist(key, arr):
        hists[key] += _device_abs_hist(arr, max_abs[key], num_bins)

    def tap(mon_name, arr):
        key = name_of.get(mon_name)
        if key is not None:
            add_hist(key, arr)

    def on_batch(batch):
        for n, d in zip(data_names, batch.data):
            if (n, 0) in hists:
                add_hist((n, 0), d)

    with _calib_timer():
        _calibration_forward(sym, arg_params, aux_params, data_names,
                             label_names, calib_data, num_calib_examples,
                             tap, on_batch)

        ranges = dict(naive)  # params keep exact min/max
        for k in act_keys:
            edges = np.linspace(0.0, max_abs[k], num_bins + 1)
            t = _entropy_threshold(hists[k], edges)
            ranges[k] = (-t, t)
            if logger:
                logger.info(
                    "entropy calib %s: max|x| %.4f -> threshold %.4f",
                    k, max_abs[k], t)
    return ranges


def symbol_digest(sym):
    """Structural digest of a Symbol: the graph JSON with gensym'd
    op-node names canonicalized (``fullyconnected0`` vs
    ``fullyconnected1`` across builds of the same block), variable names
    kept (they bind the params). One shared helper so the serving
    Predictor's AOT fingerprint and CalibrationTable model-identity use
    THE SAME notion of "same model"."""
    graph = json.loads(sym.tojson())
    for i, node in enumerate(graph.get("nodes", ())):
        if node.get("op") != "null":
            node["name"] = f"n{i}"
    return hashlib.sha256(
        json.dumps(graph, sort_keys=True).encode()).hexdigest()[:16]


class CalibrationMismatchError(MXNetError):
    """A CalibrationTable does not belong to the model it is being
    applied to — different graph structure, missing thresholds, or
    drifted parameter ranges. Raised instead of quantizing with stale
    scales: mis-calibrated int8 answers are silently wrong, an error is
    recoverable. Structured: ``model_digest`` (table's vs model's),
    ``missing`` (quantization targets without thresholds), ``drifted``
    (params whose current range left the table's)."""

    def __init__(self, msg, model_digest=None, missing=(), drifted=()):
        super().__init__(msg)
        self.model_digest = model_digest
        self.missing = tuple(missing)
        self.drifted = tuple(drifted)


class CalibrationTable:
    """Shippable calibration result: per-tensor thresholds + calibration
    provenance, saved as JSON next to the params file so serving hosts
    quantize WITHOUT calibration data (docs/quantization.md).

    ``thresholds``: ``{(producer_name, slot): (min, max)}`` — the keys
    :func:`quantize_model` consumes as ``calib_ranges``. ``model_digest``
    pins the table to the graph it was calibrated on (the BN-FOLDED
    graph, when folding is part of the deploy flow)."""

    VERSION = 1

    def __init__(self, thresholds, calib_mode, num_examples=0,
                 quantized_dtype="int8", model_digest=None, num_bins=None):
        self.thresholds = {tuple(k): (float(v[0]), float(v[1]))
                           for k, v in thresholds.items()}
        self.calib_mode = calib_mode
        self.num_examples = int(num_examples)
        self.quantized_dtype = quantized_dtype
        self.model_digest = model_digest
        self.num_bins = num_bins

    def digest(self):
        """Digest of the quantization-relevant content (thresholds +
        mode + dtype): the AOT compile-cache ingredient — a recalibrated
        table can never false-hit a stale compiled program."""
        blob = json.dumps({
            "thresholds": sorted((f"{n}:{s}", lo, hi) for (n, s), (lo, hi)
                                 in self.thresholds.items()),
            "calib_mode": self.calib_mode,
            "quantized_dtype": self.quantized_dtype,
        }, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_json(self):
        return json.dumps({
            "version": self.VERSION,
            "calib_mode": self.calib_mode,
            "quantized_dtype": self.quantized_dtype,
            "num_examples": self.num_examples,
            "num_bins": self.num_bins,
            "model_digest": self.model_digest,
            "thresholds": {f"{n}:{s}": [lo, hi] for (n, s), (lo, hi)
                           in sorted(self.thresholds.items())},
        }, sort_keys=True, indent=1)

    def save(self, path):
        from ..resilience.checkpoint import atomic_write_bytes

        atomic_write_bytes(path, self.to_json().encode())
        _STATS["calib_tables_saved"] += 1
        return path

    @classmethod
    def from_json(cls, text):
        d = json.loads(text)
        if d.get("version") != cls.VERSION:
            raise MXNetError(
                f"CalibrationTable version {d.get('version')!r} is not "
                f"supported (expected {cls.VERSION})")
        thresholds = {}
        for key, (lo, hi) in d["thresholds"].items():
            name, _, slot = key.rpartition(":")
            thresholds[(name, int(slot))] = (lo, hi)
        return cls(thresholds, d["calib_mode"],
                   num_examples=d.get("num_examples", 0),
                   quantized_dtype=d.get("quantized_dtype", "int8"),
                   model_digest=d.get("model_digest"),
                   num_bins=d.get("num_bins"))

    @classmethod
    def load(cls, path):
        with open(path) as f:
            table = cls.from_json(f.read())
        _STATS["calib_tables_loaded"] += 1
        return table

    def stale_clone(self):
        """A copy whose model identity is wrong — the shape of a stale
        table shipped against a newer model. Used by the
        ``int8_calib_mismatch`` fault drill (resilience/faults.py) so
        the detection path is exercisable deterministically."""
        clone = CalibrationTable(
            self.thresholds, self.calib_mode, self.num_examples,
            self.quantized_dtype,
            model_digest="0" * 16, num_bins=self.num_bins)
        return clone

    def validate_for(self, sym, arg_params=None, model_digest=None):
        """Threshold-drift detection: raise
        :class:`CalibrationMismatchError` unless this table matches
        ``sym`` — same structural digest (when both sides carry one),
        a threshold for every quantization target, and (when
        ``arg_params`` is given) parameter value ranges still inside the
        table's recorded ranges (a re-trained weight outside its
        calibrated range would silently clip)."""
        digest = model_digest or symbol_digest(sym)
        problems = []
        if self.model_digest is not None and digest != self.model_digest:
            problems.append(
                f"model digest {digest} != table digest "
                f"{self.model_digest}")
        targets = _quant_targets(sym)
        missing = sorted(f"{n}[{s}]" for (n, s) in targets
                         if (n, s) not in self.thresholds)
        if missing:
            problems.append(f"no thresholds for targets {missing}")
        drifted = []
        if arg_params is not None:
            for (n, s) in sorted(targets):
                if n not in arg_params or (n, s) not in self.thresholds:
                    continue
                # on-device reduction, scalar-pair pull — a fleet-replica
                # rebuild must not ship every weight tensor to the host
                # just to drift-check it
                lo, hi = _device_minmax(arg_params[n])
                tlo, thi = self.thresholds[(n, s)]
                span = max(abs(tlo), abs(thi), 1e-20)
                if lo < tlo - 1e-5 * span or hi > thi + 1e-5 * span:
                    drifted.append(f"{n}[{s}] value range ({lo:.6g}, "
                                   f"{hi:.6g}) left calibrated "
                                   f"({tlo:.6g}, {thi:.6g})")
        if drifted:
            problems.append(f"param ranges drifted: {drifted}")
        if problems:
            _STATS["calib_mismatches"] += 1
            raise CalibrationMismatchError(
                "calibration table does not match this model — "
                "re-calibrate instead of serving mis-scaled int8 "
                "answers: " + "; ".join(problems),
                model_digest=self.model_digest, missing=missing,
                drifted=drifted)
        return self


def calibrate(sym, arg_params, aux_params, calib_data,
              calib_mode="entropy", data_names=("data",),
              label_names=("softmax_label",), num_calib_examples=None,
              num_bins=None, logger=None):
    """Run calibration as a standalone product step and return a
    :class:`CalibrationTable` (thresholds + mode + sample count +
    model digest) ready to ``save()`` and ship to serving hosts.

    Calibrate the graph you will DEPLOY: if the serving flow folds
    BatchNorm (``Predictor.quantize`` does), pass the folded symbol —
    the table's model digest pins exactly that graph."""
    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"calibrate: calib_mode must be naive|entropy, "
                         f"got {calib_mode!r}")
    collect = (_collect_ranges if calib_mode == "naive"
               else _collect_entropy_ranges)
    kwargs = {} if calib_mode == "naive" else {"num_bins": num_bins}
    seen_out = []
    ranges = collect(sym, arg_params, aux_params, data_names, label_names,
                     calib_data, num_calib_examples, logger=logger,
                     seen_out=seen_out, **kwargs)
    return CalibrationTable(ranges, calib_mode,
                            num_examples=seen_out[0] if seen_out else 0,
                            num_bins=_calib_bins(num_bins)
                            if calib_mode == "entropy" else None,
                            model_digest=symbol_digest(sym))


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), excluded_sym_names=(),
                   calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   quantize_mode="fake", calib_table=None, logger=None):
    """Quantize a symbolic model (contrib/quantization.py:quantize_model).

    calib_mode: 'none' (runtime min/max), 'naive' (min/max over
    calib_data), or 'entropy' (KL-optimal clip thresholds,
    calibrate.cc). quantize_mode: 'fake' (int8 grid, fp32 compute) or
    'full' (real int8 kernels, int32 MXU accumulation — requires
    calibration). ``calib_table`` (a :class:`CalibrationTable` or a path
    to a saved one) supplies thresholds WITHOUT calibration data — it is
    validated against the model first (stale table -> structured
    :class:`CalibrationMismatchError`, never silent accuracy loss).
    Returns (quantized_symbol, arg_params, aux_params).
    """
    if quantized_dtype not in ("int8", "uint8"):
        raise MXNetError("quantized_dtype must be int8 or uint8")
    ranges = None
    if calib_table is not None and calib_data is not None:
        # never silently prefer one: a stale configured table shadowing
        # fresh calibration data is exactly the silent-accuracy-loss
        # class the table validation exists to prevent
        raise MXNetError(
            "quantize_model: pass calib_table OR calib_data, not both "
            "(a pre-shipped table and a fresh calibration run cannot "
            "both win)")
    if calib_table is not None:
        if isinstance(calib_table, str):
            calib_table = CalibrationTable.load(calib_table)
        # the int8_calib_mismatch chaos drill swaps in a stale clone
        # here, proving validation catches it on the REAL apply path
        calib_table = _faults.maybe_calib_table_drift(calib_table)
        calib_table.validate_for(sym, arg_params=arg_params)
        ranges = dict(calib_table.thresholds)
    elif calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} requires "
                             "calib_data")
        collect = (_collect_ranges if calib_mode == "naive"
                   else _collect_entropy_ranges)
        ranges = collect(sym, arg_params, aux_params, data_names,
                         label_names, calib_data, num_calib_examples,
                         logger=logger)
    elif calib_mode != "none":
        raise MXNetError(f"unsupported calib_mode {calib_mode!r} "
                         "(supported: 'none', 'naive', 'entropy')")
    if quantize_mode == "full" and ranges is None:
        raise MXNetError("quantize_mode='full' requires calibration "
                         "(calib_mode 'naive' or 'entropy')")
    if quantize_mode == "full" and quantized_dtype != "int8":
        raise MXNetError("quantize_mode='full' kernels are symmetric "
                         "int8; use quantized_dtype='int8'")
    if quantize_mode == "full":
        # quantize weights/biases OFFLINE (the reference's params step):
        # inference graphs carry int8 params, not per-step re-quantization
        from ..ndarray import ndarray as _nd

        offline_in = {k: v.asnumpy() for k, v in arg_params.items()}
        offline_out = {}
        qsym = quantize_graph(sym, excluded_sym_names, quantized_dtype,
                              ranges, quantize_mode=quantize_mode,
                              offline_params=offline_in,
                              offline_out=offline_out)
        # integer-grid propagation: pool/relu/residual-add boundaries stay
        # int8; requantize replaces quantize(dequantize(int32)) chains
        qsym = _int8_grid_propagate(qsym)
        new_args = {k: _nd.array(v, dtype=v.dtype)
                    for k, v in offline_out.items()}
        live = set(qsym.list_arguments())
        for k, v in arg_params.items():
            if k in live:  # fp32 params still consumed (e.g. excluded ops)
                new_args[k] = v
        return qsym, new_args, aux_params
    qsym = quantize_graph(sym, excluded_sym_names, quantized_dtype, ranges,
                          quantize_mode=quantize_mode)
    return qsym, arg_params, aux_params


# ---------------------------------------------------------------------------
# round 5: whole-graph int8 — BN folding + integer-grid propagation, so a
# quantized ResNet stays on the int8 grid through pool / relu / residual-add
# instead of bouncing through dequantize at every boundary
# (reference: src/operator/quantization/quantized_{pooling,activation,
# elemwise_add}.cc + the BN-fold every deployed int8 CNN applies)
# ---------------------------------------------------------------------------

def fold_batch_norm(sym, arg_params, aux_params, eps_default=1e-3):
    """Fold inference-mode BatchNorm into the preceding Convolution.

    conv -> BN(gamma, beta, mean, var) becomes conv' with
      w' = w * gamma / sqrt(var + eps)   (per output channel)
      b' = (b - mean) * gamma / sqrt(var + eps) + beta
    Returns (new_sym, new_arg_params, new_aux_params). Only BN nodes whose
    sole input is a Convolution output are folded; others stay (their
    moving stats remain in aux). The fold is exact for inference
    (use_global_stats semantics)."""
    from ..ndarray import ndarray as _nd
    from ..symbol.symbol import Symbol, _Node

    args = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
            for k, v in arg_params.items()}
    auxs = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
            for k, v in aux_params.items()}
    mapping = {}

    def var_of(node_inputs, idx):
        n, _ = node_inputs[idx]
        return n.name if n.is_var else None

    def cloned(node):
        if id(node) in mapping:
            return mapping[id(node)]
        new = _Node(node.op, node.name, params=dict(node.params),
                    attrs=dict(node.attrs))
        new.aux_mark = node.aux_mark
        mapping[id(node)] = new
        new.inputs = [(cloned(n), s) for n, s in node.inputs]
        if node.op != "BatchNorm":
            return new
        src, src_slot = node.inputs[0]
        if src.is_var or src.op != "Convolution" or src_slot != 0:
            return new
        gamma_n = var_of(node.inputs, 1)
        beta_n = var_of(node.inputs, 2)
        mean_n = var_of(node.inputs, 3)
        var_n = var_of(node.inputs, 4)
        w_n = var_of(src.inputs, 1)
        if None in (gamma_n, beta_n, mean_n, var_n, w_n) or \
                w_n not in args or mean_n not in auxs:
            return new
        eps = float(node.params.get("eps", eps_default))
        fix_gamma = bool(node.params.get("fix_gamma", True))
        gamma = (np.ones_like(auxs[mean_n]) if fix_gamma
                 else args[gamma_n])
        beta = args[beta_n]
        mean, var = auxs[mean_n], auxs[var_n]
        scale = gamma / np.sqrt(var + eps)
        w = args[w_n]
        layout = src.params.get("layout")
        # weight layouts: OIHW (channels-first) and OHWI (channels-last)
        # both keep O on axis 0
        args[w_n + "_bnfold"] = (
            w * scale.reshape((-1,) + (1,) * (w.ndim - 1))).astype(w.dtype)
        b_prev = 0.0
        bias_n = var_of(src.inputs, 2) if len(src.inputs) > 2 else None
        if bias_n is not None and bias_n in args:
            b_prev = args[bias_n]
        args[w_n + "_bnfold_bias"] = (
            (b_prev - mean) * scale + beta).astype(beta.dtype)
        conv_clone = cloned(src)  # already cloned as new.inputs[0]
        from ..symbol.symbol import Variable as _Var

        wv = _Var(w_n + "_bnfold")._outputs[0][0]
        bv = _Var(w_n + "_bnfold_bias")._outputs[0][0]
        folded = _Node("Convolution", src.name + "_bnfold",
                       params={**src.params, "no_bias": False},
                       inputs=[conv_clone.inputs[0], (wv, 0), (bv, 0)])
        mapping[id(node)] = folded
        return folded

    out_sym = Symbol([(cloned(n), s) for n, s in sym._outputs])
    live_args = set(out_sym.list_arguments())
    new_args = {k: _nd.array(v) for k, v in args.items() if k in live_args}
    live_aux = set(out_sym.list_auxiliary_states())
    new_aux = {k: _nd.array(v) for k, v in auxs.items() if k in live_aux}
    return out_sym, new_args, new_aux


_I32_PRODUCERS = ("_contrib_quantized_conv",
                  "_contrib_quantized_fully_connected",
                  "_contrib_quantized_elemwise_add",
                  "_contrib_quantized_elemwise_mul")
_I8_PRODUCERS = ("_contrib_quantize_v2", "_contrib_requantize")
_GRID_PASSTHROUGH = ("_contrib_quantized_pooling", "_contrib_quantized_act",
                     "_contrib_quantized_flatten")


def _grid_of(node):
    """'int8' / 'int32' / None — which integer grid a node's output rides."""
    seen = set()
    while True:
        if node.is_var or id(node) in seen:
            return None
        seen.add(id(node))
        if node.op in _I32_PRODUCERS:
            return "int32"
        if node.op in _I8_PRODUCERS:
            return "int8"
        if node.op in _GRID_PASSTHROUGH:
            node = node.inputs[0][0]
            continue
        return None


def _int8_grid_propagate(sym):
    """Peephole pass over a full-mode quantized graph: ops that can run on
    the integer grid consume their producer's int8/int32 triple directly.

    - quantize_v2(dequantize(int32 triple))  -> requantize(triple)
    - Pooling(dequantize(int8 triple))       -> quantized_pooling
    - Activation-relu(dequantize(int8))      -> quantized_act
    - elemwise_add(deq(int8), deq(int8))     -> quantized_elemwise_add
    Every rewritten node keeps its original identity as the boundary
    dequantize, so fp32 consumers are untouched; chained int8 consumers
    then fold through THEIR dequantize, and XLA DCEs the dead boundaries.
    """
    from ..symbol.symbol import _Node

    def deq_src(inp):
        n, slot = inp
        if not n.is_var and n.op == "_contrib_dequantize" and slot == 0:
            q, qs = n.inputs[0]
            return n, q
        return None, None

    changed = True
    while changed:
        changed = False
        # one reverse index per pass: producer (node, slot) -> its
        # quantize/requantize consumer (reused by the residual-add fold)
        quant_of = {}
        for n2 in sym._topo_nodes():
            if not n2.is_var and n2.op in _I8_PRODUCERS and n2.inputs:
                quant_of[(id(n2.inputs[0][0]), n2.inputs[0][1])] = n2
        for node in sym._topo_nodes():
            if node.is_var:
                continue
            if node.op == "_contrib_quantize_v2":
                dq, q = deq_src(node.inputs[0])
                if dq is not None and _grid_of(q) == "int32":
                    node.op = "_contrib_requantize"
                    node.inputs = list(dq.inputs)
                    node.params = {k: node.params[k] for k in
                                   ("min_calib_range", "max_calib_range")
                                   if k in node.params}
                    changed = True
            elif node.op == "Pooling":
                dq, q = deq_src(node.inputs[0])
                layout_ok = (node.params.get("layout") or "NCHW")[1] == "C"
                if dq is not None and layout_ok and \
                        _grid_of(q) is not None:
                    qp_params = {k: v for k, v in node.params.items()
                                 if k in ("kernel", "stride", "pad",
                                          "pool_type", "global_pool",
                                          "pooling_convention",
                                          "count_include_pad", "layout")}
                    qp = _Node("_contrib_quantized_pooling",
                               node.name + "_int8",
                               params=qp_params,
                               inputs=list(dq.inputs))
                    node.op = "_contrib_dequantize"
                    node.params = {}
                    node.inputs = [(qp, 0), (qp, 1), (qp, 2)]
                    changed = True
            elif node.op == "Activation" and \
                    node.params.get("act_type", "relu") == "relu":
                dq, q = deq_src(node.inputs[0])
                if dq is not None and _grid_of(q) is not None:
                    qa = _Node("_contrib_quantized_act",
                               node.name + "_int8",
                               params={"act_type": "relu"},
                               inputs=list(dq.inputs))
                    node.op = "_contrib_dequantize"
                    node.params = {}
                    node.inputs = [(qa, 0), (qa, 1), (qa, 2)]
                    changed = True
            elif node.op in ("elemwise_add", "broadcast_add", "_plus"):
                # an operand joins the int8-grid add if it is (a) a
                # dequantize of an int8 triple, (b) a dequantize of an
                # int32 triple (requantized first), or (c) an fp32 value
                # some OTHER consumer already quantizes (the residual-skip
                # case: the next conv's quantize_v2 holds its triple —
                # reuse it instead of quantizing twice)
                def int8_triple(inp):
                    dq, q = deq_src(inp)
                    if dq is not None:
                        g = _grid_of(q)
                        if g == "int8":
                            return list(dq.inputs)
                        if g == "int32":
                            rq = _Node("_contrib_requantize",
                                       q.name + "_rq",
                                       inputs=list(dq.inputs))
                            return [(rq, 0), (rq, 1), (rq, 2)]
                    qn = quant_of.get((id(inp[0]), inp[1]))
                    if qn is not None:
                        return [(qn, 0), (qn, 1), (qn, 2)]
                    return None

                ta = int8_triple(node.inputs[0])
                tb = int8_triple(node.inputs[1])
                if ta is not None and tb is not None:
                    qadd = _Node(
                        "_contrib_quantized_elemwise_add",
                        node.name + "_int8",
                        inputs=[ta[0], tb[0], ta[1], ta[2], tb[1], tb[2]])
                    node.op = "_contrib_dequantize"
                    node.params = {}
                    node.inputs = [(qadd, 0), (qadd, 1), (qadd, 2)]
                    changed = True
    return sym
