"""Experimental / contributed namespaces.

Parity: python/mxnet/contrib/ — the reference parks AMP, ONNX, quantization,
tensorboard, and the estimator fit-API here. In this build mx.amp is a
first-class top-level module; `contrib.amp` aliases it for scripts written
against the reference layout.
"""
from .. import amp  # noqa: F401  (contrib.amp parity alias)


def __getattr__(name):
    import importlib

    lazy = {
        "tensorboard": ".tensorboard",
        "quantization": ".quantization",
        "svrg_optimization": ".svrg_optimization",
        "onnx": ".onnx",
    }
    if name in lazy:
        mod = importlib.import_module(lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.contrib' has no attribute {name!r}")
