"""Runtime feature detection.

Parity: python/mxnet/runtime.py (feature_list/Features over src/libinfo.cc).
TPU-native: features reflect the live JAX/PJRT environment instead of
compile-time cmake flags — the build has no compile-time variants, so the
flags describe which backends/capabilities this process can actually use.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "Features", "feature_list"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax

    feats = {}

    def add(name, enabled):
        feats[name] = Feature(name, bool(enabled))

    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        platforms = set()
    add("TPU", any(p not in ("cpu",) for p in platforms))
    add("CPU", True)
    add("CUDA", False)
    add("CUDNN", False)
    add("MKLDNN", False)
    add("OPENCV", _has("PIL"))
    add("BLAS_OPEN", True)          # XLA's dot lowering
    add("LAPACK", True)             # jnp.linalg
    add("F16C", True)               # bf16/f16 casts are native
    add("JIT", True)                # XLA jit
    add("PALLAS", _has("jax.experimental.pallas"))
    add("DIST_KVSTORE", True)       # kvstore + jax.distributed bootstrap
    add("INT64_TENSOR_SIZE", False)  # x64 disabled by default
    add("SIGNAL_HANDLER", True)
    add("PROFILER", True)           # mx.profiler over jax.profiler
    return feats


def _has(mod):
    import importlib.util

    try:
        return importlib.util.find_spec(mod) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


class Features(dict):
    """Mapping name -> Feature, like the reference's Features (runtime.py)."""

    instance = None

    def __init__(self):
        super().__init__(_detect())

    def __repr__(self):
        return "[" + ", ".join(
            f"{f.name}{'' if f.enabled else ' (disabled)'}"
            for f in self.values()) + "]"

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"feature '{feature_name}' does not exist")
        return self[feature_name].enabled


def feature_list():
    """Check the library for compile-time/runtime features.

    Returns a list of Feature objects (parity: runtime.py feature_list)."""
    if Features.instance is None:
        Features.instance = Features()
    return list(Features.instance.values())
