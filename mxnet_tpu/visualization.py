"""Network visualization.

Parity: python/mxnet/visualization.py — print_summary (layer table with
parameter counts) and plot_network (graphviz digraph). Works on this build's
Symbol JSON graph; graphviz rendering is optional (falls back with a clear
error if the package is missing, like the reference).
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _conf(symbol):
    conf = json.loads(symbol.tojson())
    return conf["nodes"], conf.get("heads", [])


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Print a table of layers/shapes/params (visualization.py print_summary)."""
    show_shape = shape is not None
    shape_dict = {}
    if show_shape:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    nodes, _ = _conf(symbol)
    heads = set()
    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, pos):
        line = ""
        for i, f in enumerate(fields):
            line += str(f)
            line = line[:pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        if op != "null":
            for item in node.get("inputs", []):
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
        cur_param = 0
        attrs = node.get("attrs", {}) or {}
        if op == "null":
            # parameter node: count from inferred shape
            key = node["name"]
            if show_shape and key in shape_dict:
                cur_param = 1
                for s in shape_dict[key]:
                    cur_param *= s
        name = node["name"]
        first_connection = pre_node[0] if pre_node else ""
        fields = [f"{name}({op})",
                  "x".join(str(s) for s in out_shape) if out_shape else "",
                  cur_param, first_connection]
        print_row(fields, positions)
        for conn in pre_node[1:]:
            print_row(["", "", "", conn], positions)
        total_params[0] += cur_param

    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        name = node["name"]
        if op != "null":
            key = name + "_output"
            if show_shape and key in shape_dict:
                out_shape = list(shape_dict[key])
        elif show_shape and name in shape_dict:
            out_shape = list(shape_dict[name])
        print_layer_summary(node, out_shape)
        print(("=" if i == len(nodes) - 1 else "_") * line_length)
    print(f"Total params: {total_params[0]}")
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the symbol graph
    (visualization.py plot_network). Requires the `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the `graphviz` python package; it is "
            "not bundled in this environment — use print_summary for a "
            "text rendering") from e
    nodes, _ = _conf(symbol)
    draw_shape = shape is not None
    shape_dict = {}
    if draw_shape:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs or {})
    dot = Digraph(name=title, format=save_format)
    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        attrs = {"name": name}
        label = name
        if op == "null":
            if name.endswith(("_weight", "_bias", "_beta", "_gamma",
                              "_moving_mean", "_moving_var",
                              "_running_mean", "_running_var")):
                if hide_weights:
                    hidden_nodes.add(name)
                continue
            attrs["fillcolor"] = "#8dd3c7"
            label = name
        else:
            params = node.get("attrs", {}) or {}
            label = f"{op}\n{name}"
            attrs["fillcolor"] = {
                "Convolution": "#fb8072", "FullyConnected": "#fb8072",
                "BatchNorm": "#bebada", "Activation": "#ffffb3",
                "Pooling": "#80b1d3", "Concat": "#fdb462",
            }.get(op, "#fccde5")
        dot.node(name=name, label=label, **{**node_attr, **attrs})
    name2idx = {n["name"]: i for i, n in enumerate(nodes)}
    for node in nodes:
        if node["op"] == "null" or node["name"] in hidden_nodes:
            continue
        for item in node.get("inputs", []):
            input_node = nodes[item[0]]
            input_name = input_node["name"]
            if input_name in hidden_nodes:
                continue
            attrs = {"dir": "back", "arrowtail": "open"}
            if draw_shape:
                key = input_name + ("_output" if input_node["op"] != "null"
                                    else "")
                if key in shape_dict:
                    attrs["label"] = "x".join(
                        str(s) for s in shape_dict[key])
            dot.edge(tail_name=node["name"], head_name=input_name, **attrs)
    return dot
