"""Detection-aware image augmentation + iterator.

Capability parity with the reference's python/mxnet/image/detection.py
(DetAugmenter hierarchy, CreateDetAugmenter, ImageDetIter — the input stack
of example/ssd). Host-side numpy/PIL preprocessing; boxes ride along with
every geometric transform.

Label convention (same as the reference): per image an (N, 5+) float array,
rows [class_id, xmin, ymin, xmax, ymax, ...] with coordinates normalized to
[0, 1]; class_id < 0 marks padding rows. Batched labels are padded with -1
to the widest image in the dataset.
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base class (detection.py:DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


def _to_np(img, dtype=np.float32):
    """Pixel augmenters speak NDArray (reference API); the det chain works
    in numpy — normalize at the seams."""
    if hasattr(img, "asnumpy"):
        img = img.asnumpy()
    return np.asarray(img, dtype=dtype)


class DetBorrowAug(DetAugmenter):
    """Lift a pixel-only Augmenter into the det chain (labels untouched)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        from ..ndarray import ndarray as _nd

        out = self.augmenter(_nd.array(src))
        return _to_np(out), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter from a list (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or np.random.random() < self.skip_prob:
            return src, label
        i = np.random.randint(len(self.aug_list))
        return self.aug_list[i](src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if np.random.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


def _box_coverage(boxes, crop):
    """Fraction of each box's area inside crop (both normalized corner)."""
    ix = np.maximum(
        np.minimum(boxes[:, 3], crop[2]) - np.maximum(boxes[:, 1], crop[0]),
        0)
    iy = np.maximum(
        np.minimum(boxes[:, 4], crop[3]) - np.maximum(boxes[:, 2], crop[1]),
        0)
    inter = ix * iy
    area = np.maximum((boxes[:, 3] - boxes[:, 1]) *
                      (boxes[:, 4] - boxes[:, 2]), 1e-12)
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by object coverage (detection.py
    DetRandomCropAug). Objects whose coverage falls below
    `min_eject_coverage` are dropped; surviving boxes are clipped and
    re-normalized to the crop."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        valid = label[label[:, 0] >= 0]
        for _ in range(self.max_attempts):
            area = np.random.uniform(*self.area_range)
            ar = np.random.uniform(*self.aspect_ratio_range)
            w = min(np.sqrt(area * ar), 1.0)
            h = min(np.sqrt(area / ar), 1.0)
            x0 = np.random.uniform(0, 1 - w)
            y0 = np.random.uniform(0, 1 - h)
            crop = (x0, y0, x0 + w, y0 + h)
            if valid.size == 0:
                return crop
            cov = _box_coverage(valid, crop)
            if (cov >= self.min_object_covered).any():
                return crop
        return None

    def __call__(self, src, label):
        crop = self._sample_crop(label)
        if crop is None:
            return src, label
        h, w = src.shape[:2]
        x0, y0, x1, y1 = crop
        px0, py0 = int(x0 * w), int(y0 * h)
        px1, py1 = max(int(x1 * w), px0 + 1), max(int(y1 * h), py0 + 1)
        cw, ch = (px1 - px0) / w, (py1 - py0) / h
        nx0, ny0 = px0 / w, py0 / h
        # filter/clip boxes against the crop BEFORE touching pixels so an
        # all-ejected crop can be abandoned cleanly
        out = np.full_like(label, -1.0)
        k = 0
        for row in label:
            if row[0] < 0:
                continue
            cov = _box_coverage(row[None, :], (nx0, ny0, nx0 + cw, ny0 + ch))[0]
            if cov < self.min_eject_coverage:
                continue
            bx0 = (max(row[1], nx0) - nx0) / cw
            by0 = (max(row[2], ny0) - ny0) / ch
            bx1 = (min(row[3], nx0 + cw) - nx0) / cw
            by1 = (min(row[4], ny0 + ch) - ny0) / ch
            if bx1 <= bx0 or by1 <= by0:
                continue
            out[k] = row
            out[k, 1:5] = (bx0, by0, bx1, by1)
            k += 1
        if k == 0:
            return src, label
        return src[py0:py1, px0:px1], out


class DetRandomPadAug(DetAugmenter):
    """Place the image on a larger canvas (zoom-out) and rescale boxes."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = np.random.uniform(*self.area_range)
            ar = np.random.uniform(*self.aspect_ratio_range)
            nw = int(w * np.sqrt(area * ar))
            nh = int(h * np.sqrt(area / ar))
            if nw < w or nh < h:
                continue
            x0 = np.random.randint(0, nw - w + 1)
            y0 = np.random.randint(0, nh - h + 1)
            canvas = np.empty((nh, nw, src.shape[2]), dtype=src.dtype)
            canvas[:] = np.asarray(self.pad_val, dtype=src.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = src
            out = label.copy()
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * w + x0) / nw
            out[valid, 3] = (out[valid, 3] * w + x0) / nw
            out[valid, 2] = (out[valid, 2] * h + y0) / nh
            out[valid, 4] = (out[valid, 4] * h + y0) / nh
            return canvas, out
        return src, label


class _DetForceResize(DetAugmenter):
    def __init__(self, size):  # size = (w, h)
        super().__init__(size=size)
        self.size = size

    def __call__(self, src, label):
        return _to_np(_img.imresize(src, self.size[0], self.size[1])), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Build the standard SSD augmentation chain (detection.py:
    CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (min(area_range[0], 1.0),
                                 min(area_range[1], 1.0)),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(_DetForceResize((data_shape[2], data_shape[1])))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(_img.ColorJitterAug(
            brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(_img.HueJitterAug(hue)))
    if pca_noise > 0:
        auglist.append(DetBorrowAug(_img.LightingAug(
            pca_noise,
            np.array([55.46, 4.794, 1.148]),
            np.array([[-0.5675, 0.7192, 0.4009],
                      [-0.5808, -0.0045, -0.814],
                      [-0.5836, -0.6948, 0.4203]]))))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(_img.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.asarray(mean).any():
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter:
    """Detection iterator (detection.py:ImageDetIter). Sources: in-memory
    ``imglist`` [(label, path), ...] or ``path_imglist`` in the reference's
    det .lst format (idx\\tA\\tB\\t[extras]\\t(cls x1 y1 x2 y2)*N\\tpath,
    A = header width incl. A and B, B = object width).

    Yields DataBatch: data (B,C,H,W) float32, label (B, max_obj, obj_width)
    padded with -1.
    """

    def __init__(self, batch_size, data_shape, path_imglist=None,
                 path_root="", imglist=None, shuffle=False, aug_list=None,
                 data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        from ..io import DataDesc

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.path_root = path_root
        self.shuffle = shuffle
        entries = []
        if path_imglist:
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    header_w = int(float(parts[1]))
                    obj_w = int(float(parts[2]))
                    vals = [float(x) for x in parts[1:-1]]
                    objs = np.asarray(vals[header_w:], dtype=np.float32)
                    objs = objs.reshape(-1, obj_w)
                    entries.append((objs, parts[-1]))
        elif imglist is not None:
            for label, path in imglist:
                arr = np.asarray(label, dtype=np.float32)
                if arr.ndim == 1:
                    arr = arr.reshape(-1, 5)
                entries.append((arr, path))
        else:
            raise MXNetError("need path_imglist or imglist")
        if not entries:
            raise MXNetError("empty detection image list")
        self._entries = entries
        self.obj_width = max(e[0].shape[1] for e in entries)
        self.max_objects = max(e[0].shape[0] for e in entries)
        if aug_list is None:
            aug_list = CreateDetAugmenter(self.data_shape, **kwargs)
        self.auglist = aug_list
        if last_batch_handle == "roll_over":
            import warnings

            warnings.warn("ImageDetIter: last_batch_handle='roll_over' is "
                          "not supported; using 'pad'")
            last_batch_handle = "pad"
        self.last_batch_handle = last_batch_handle
        self._data_name, self._label_name = data_name, label_name
        self.provide_data = [DataDesc(
            data_name, (batch_size,) + self.data_shape, np.float32)]
        self._refresh_label_desc()
        self._order = np.arange(len(entries))
        self.cur = 0
        self.reset()

    def _refresh_label_desc(self):
        from ..io import DataDesc

        self.provide_label = [DataDesc(
            self._label_name,
            (self.batch_size, self.max_objects, self.obj_width),
            np.float32)]

    def __iter__(self):
        return self

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self._order)
        self.cur = 0

    def _read(self, i):
        label, path = self._entries[self._order[i]]
        img = _to_np(_img.imread(os.path.join(self.path_root, path)))
        lab = np.full((self.max_objects, self.obj_width), -1.0, np.float32)
        lab[:label.shape[0], :label.shape[1]] = label
        for aug in self.auglist:
            img, lab = aug(img, lab)
        c, h, w = self.data_shape
        if img.shape[:2] != (h, w):
            img = _to_np(_img.imresize(img, w, h))
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, (2, 0, 1)), lab

    def next(self):
        from ..io import DataBatch
        from ..ndarray import ndarray as _nd

        n = len(self._entries)
        if self.cur >= n:
            raise StopIteration
        if self.last_batch_handle == "discard" and \
                self.cur + self.batch_size > n:
            raise StopIteration
        bsz = self.batch_size
        c, h, w = self.data_shape
        data = np.zeros((bsz, c, h, w), np.float32)
        label = np.full((bsz, self.max_objects, self.obj_width), -1.0,
                        np.float32)
        pad = 0
        for j in range(bsz):
            idx = self.cur + j
            if idx >= n:
                idx %= n
                pad += 1
            data[j], label[j] = self._read(idx)
        self.cur += bsz
        return DataBatch(data=[_nd.array(data)], label=[_nd.array(label)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()

    def sync_label_shape(self, it, verbose=False):
        """Align label widths between train/val iterators (reference API)."""
        shape = (max(self.max_objects, it.max_objects),
                 max(self.obj_width, it.obj_width))
        self.max_objects = it.max_objects = shape[0]
        self.obj_width = it.obj_width = shape[1]
        self._refresh_label_desc()
        it._refresh_label_desc()
        return it
