"""mx.image (parity: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from . import image
from . import detection
from .detection import (CreateDetAugmenter, DetAugmenter,  # noqa: F401
                        DetBorrowAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug,
                        DetRandomSelectAug, ImageDetIter)
