"""mx.image — host-side image loading + augmentation.

Parity: python/mxnet/image/image.py (+ src/io/image_aug_default.cc). The
reference decoded/augmented with OpenCV on CPU worker threads; here PIL +
numpy do the host-side work (the hot path belongs to the C++ loader in
src/io, and per-batch math to the jitted step).
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "random_size_crop",
           "CreateAugmenter", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "ImageIter"]


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError as e:
        raise MXNetError("mx.image requires PIL in this build") from e


def imread(filename, flag=1, to_rgb=True):
    """Read image from file (image.py:81)."""
    img = _pil().open(filename)
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd.array(arr.astype(np.uint8))

imdecode_flags = {"color": 1, "grayscale": 0}


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an image from bytes (image.py:144)."""
    from io import BytesIO
    if isinstance(buf, nd.NDArray):
        buf = buf.asnumpy().tobytes()
    elif isinstance(buf, np.ndarray):
        buf = buf.tobytes()
    img = _pil().open(BytesIO(buf))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd.array(arr.astype(np.uint8))


def imresize(src, w, h, interp=1):
    """Resize to (w, h) (image.py:303)."""
    a = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    squeeze = a.shape[2] == 1 if a.ndim == 3 else False
    pil_img = _pil().fromarray(a[:, :, 0] if squeeze else a.astype(np.uint8))
    out = np.asarray(pil_img.resize((w, h), _pil().BILINEAR))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out.astype(a.dtype if a.dtype != np.float64 else np.float32))


def resize_short(src, size, interp=2):
    """Resize shorter edge to size (image.py:400)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop at fixed position (image.py:450)."""
    a = src.asnumpy() if isinstance(src, nd.NDArray) else src
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(nd.array(out), size[0], size[1], interp)
    return nd.array(out)


def random_crop(src, size, interp=2):
    """Random crop with resize (image.py:477)."""
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop with resize (image.py:518)."""
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop by area fraction + aspect ratio (image.py:585)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std (image.py:563)."""
    if isinstance(src, nd.NDArray) and src.dtype == np.uint8:
        src = src.astype(np.float32)
    out = src - mean
    if std is not None:
        out = out / std
    return out


class Augmenter:
    """Image augmenter base (image.py:640)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        a = src.asnumpy()
        gray = (a * self.coef).sum() * (3.0 * (1.0 - alpha) / a.size)
        return nd.array(a * alpha + gray)


class SaturationJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        a = src.asnumpy()
        gray = (a * self.coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return nd.array(a * alpha + gray)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], dtype=np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], dtype=np.float32)

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      dtype=np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return nd.array(np.dot(src.asnumpy(), t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = nd.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = nd.dot(src, self.mat)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Creates the standard augmenter list (image.py:1129)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and len(np.atleast_1d(mean)) > 0:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image data iterator with augmentation (image.py:1210). Supports
    imglist/path_imglist/path_imgrec sources; yields io.DataBatch."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", last_batch_handle="pad", **kwargs):
        from ..io import DataDesc
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self.dtype = dtype
        self.imgrec = None
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            from ..recordio import MXIndexedRecordIO, MXRecordIO
            if os.path.isfile(idx_path):
                self.imgrec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = MXRecordIO(path_imgrec, "r")
                self.seq = None
            self.imglist = None
        else:
            if path_imglist:
                entries = {}
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = np.array(
                            [float(i) for i in parts[1:-1]], dtype=np.float32)
                        entries[int(parts[0])] = (label, parts[-1])
                self.imglist = entries
            else:
                entries = {}
                for i, rec in enumerate(imglist):
                    label = np.array(rec[0] if isinstance(rec[0], (list, tuple))
                                     else [rec[0]], dtype=np.float32)
                    entries[i] = (label, rec[1])
                self.imglist = entries
            self.seq = list(self.imglist.keys())
        if num_parts > 1 and self.seq is not None:
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast", "saturation",
                         "hue", "pca_noise", "rand_gray", "inter_method")})
        else:
            self.auglist = aug_list
        self.provide_data = [DataDesc(data_name, (batch_size,) + self.data_shape,
                                      dtype)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, label_width) if
                                       label_width > 1 else (batch_size,),
                                       dtype)]
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        from ..recordio import unpack
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                img = f.read()
            return label, img
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = unpack(s)
        return header.label, img

    def next(self):
        from ..io import DataBatch
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              dtype=self.dtype)
        shape = (self.batch_size, self.label_width) if self.label_width > 1 \
            else (self.batch_size,)
        batch_label = np.zeros(shape, dtype=self.dtype)
        i = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                data = imdecode(s)
                for aug in self.auglist:
                    data = aug(data)
                arr = data.asnumpy() if isinstance(data, nd.NDArray) else data
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = self.batch_size - i
        return DataBatch(data=[nd.array(batch_data)],
                         label=[nd.array(batch_label)], pad=pad)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self
