"""Activation rematerialization (gradient mirroring).

The TPU-native counterpart of the reference's backward mirroring
(``MXNET_BACKWARD_DO_MIRROR`` read at src/executor/graph_executor.cc:357;
mirror pass src/nnvm/gradient.cc:107-148): instead of a graph pass marking
cheap nodes for recompute, the traced forward is wrapped in
``jax.checkpoint`` and XLA's scheduler recomputes non-saved activations
during the backward — trading FLOPs for HBM, which is the right trade on a
chip whose train step sits at the HBM roofline (PERF.md).

Entry points:
- ``ShardedTrainer(..., remat=...)`` — whole-forward policy remat.
- ``gluon.contrib.Remat(block)`` — segment-level remat around any block.
- env ``MXNET_BACKWARD_DO_MIRROR=1`` — reference-parity switch; picked up
  by both paths and by ``Executor`` bind.
"""
from __future__ import annotations

__all__ = ["resolve_policy", "mirror_enabled"]


def mirror_enabled():
    """True when the reference's mirroring env flag is set."""
    from .util import getenv

    v = getenv("MXNET_BACKWARD_DO_MIRROR")
    return v not in (None, "", "0", "false", "False")


def resolve_policy(spec):
    """Map a user remat spec to a jax.checkpoint policy.

    - ``True``/``None`` -> recompute everything not needed structurally
      (the strongest memory reduction; reference mirror's spirit)
    - a string -> attribute of ``jax.checkpoint_policies``
      (e.g. ``'dots_with_no_batch_dims_saveable'`` for transformer stacks,
      keeping matmul outputs and recomputing elementwise chains)
    - a callable -> used as the policy directly
    """
    import jax

    if spec is None or spec is True:
        return None
    if isinstance(spec, str):
        try:
            return getattr(jax.checkpoint_policies, spec)
        except AttributeError:
            raise ValueError(
                f"unknown remat policy '{spec}'; see jax.checkpoint_policies")
    if callable(spec):
        return spec
    raise TypeError(f"remat spec must be bool/str/callable, got {type(spec)}")
