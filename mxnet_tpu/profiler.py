"""Profiler frontend.

Parity: python/mxnet/profiler.py (set_config :33, set_state :89, dump/dumps
:151, pause/resume :193-209) over src/profiler/profiler.h:251. TPU-native:
events come from the XLA/jax profiler (xplane traces viewable in
TensorBoard/Perfetto — the modern analogue of the reference's
chrome://tracing JSON dump), plus lightweight host-side scopes/counters kept
in-process for `dumps()` aggregate tables.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "profiler_set_config", "profiler_set_state", "Task",
           "Frame", "Event", "Counter", "Marker", "scope", "dispatch_stats",
           "reset_dispatch_stats", "dispatch_ring", "record_dispatch",
           "set_dispatch_ring"]

_LOCK = threading.Lock()
_CONFIG = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": True}
_STATE = "stop"
_TRACE_DIR = None
_EVENTS = []          # host-side (name, start, dur) events
_COUNTERS = {}
_PAUSED = False

# Last-K eager-dispatch ring buffer: the forensic trail the watchdog's
# crash reports embed ("what ops ran just before the stall"). Appends are
# a deque.append from the dispatch hot path (ops.registry.dispatch), so
# the cost is ~100 ns per op. MXNET_TPU_DISPATCH_RING sizes it (0
# disables, default 64) — read ONCE at import to keep the hot path a
# bare attribute load; resize after import with set_dispatch_ring().
try:
    _RING_SIZE = int(os.environ.get("MXNET_TPU_DISPATCH_RING", "64"))
except ValueError:
    _RING_SIZE = 64
_DISPATCH_RING = deque(maxlen=_RING_SIZE) if _RING_SIZE > 0 else None
_DISPATCH_SEQ = itertools.count(1)


def set_dispatch_ring(size):
    """Resize (or with ``size<=0`` disable) the dispatch ring at
    runtime; returns the previous size. The registry reads the module
    attribute on every dispatch, so the swap takes effect immediately —
    this is the post-import counterpart of MXNET_TPU_DISPATCH_RING."""
    global _DISPATCH_RING
    prev = _DISPATCH_RING.maxlen if _DISPATCH_RING is not None else 0
    size = int(size)
    _DISPATCH_RING = deque(maxlen=size) if size > 0 else None
    return prev


def record_dispatch(name):
    """Append one dispatched op to the ring (hot path; registry calls
    the deque directly — this wrapper exists for external recorders)."""
    ring = _DISPATCH_RING
    if ring is not None:
        ring.append((next(_DISPATCH_SEQ), time.perf_counter(), name))


def dispatch_ring():
    """The last-K eagerly dispatched ops, oldest first, as
    ``{"seq", "t", "op"}`` dicts (``t`` = perf_counter seconds; compare
    entries to each other, not to the wall clock)."""
    if _DISPATCH_RING is None:
        return []
    return [{"seq": s, "t": t, "op": n}
            for s, t, n in list(_DISPATCH_RING)]


def set_config(**kwargs):
    """Configure the profiler (profiler.py:33). ``filename`` names the
    output; everything else toggles collection categories."""
    with _LOCK:  # dump()/set_state() read _CONFIG from other threads
        _CONFIG.update(kwargs)


profiler_set_config = set_config


def set_state(state="stop", profile_process="worker"):
    """'run' starts the jax trace collector, 'stop' ends it
    (profiler.py:89)."""
    global _STATE, _TRACE_DIR
    import jax

    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    with _LOCK:
        if state == "run" and _STATE == "stop":
            _TRACE_DIR = _CONFIG.get("trace_dir") or os.path.join(
                os.path.dirname(os.path.abspath(
                    _CONFIG.get("filename", "profile.json"))) or ".",
                "jax-trace")
            try:
                jax.profiler.start_trace(_TRACE_DIR)
            except Exception:
                _TRACE_DIR = None  # tracing unsupported on this backend
        elif state == "stop" and _STATE == "run":
            if _TRACE_DIR is not None:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        _STATE = state


profiler_set_state = set_state


def state():
    return _STATE


def pause(profile_process="worker"):
    """Suspend host-side event collection (profiler.py:193)."""
    global _PAUSED
    _PAUSED = True


def resume(profile_process="worker"):
    global _PAUSED
    _PAUSED = False


def dump(finished=True, profile_process="worker"):
    """Write collected host events as chrome://tracing JSON to
    ``filename`` (the xplane trace from set_state lands in trace_dir)."""
    events = []
    with _LOCK:
        for name, t0, dur, cat in _EVENTS:
            events.append({"name": name, "cat": cat, "ph": "X",
                           "ts": t0 * 1e6, "dur": dur * 1e6,
                           "pid": 0, "tid": 0})
        for name, value in _COUNTERS.items():
            events.append({"name": name, "ph": "C", "ts": time.time() * 1e6,
                           "pid": 0, "args": {name: value}})
    with open(_CONFIG.get("filename", "profile.json"), "w") as f:
        json.dump({"traceEvents": events}, f)


def dispatch_stats(reset=False, lock_timeout=None):
    """Eager-dispatch observability counters as a flat dict: per-op
    executable cache hits/misses, jax retraces, donated-buffer dispatches,
    device_put skips, and bulk-segment stats from mxnet_tpu.engine.

    Counter semantics (see docs/engine.md):
    - eager_cache_hit/miss: per-op executable cache lookups in ops.registry
    - eager_retrace: jax-level retraces (new shape/dtype specialization)
    - donated_dispatches/donated_args: calls through (and args into)
      donation-compiled executables for `mutate` ops
    - device_put_skipped/performed: inputs already committed to the target
      device vs. actually moved
    - bulk_segments/bulk_ops/bulk_cache_hit/bulk_cache_miss/
      bulk_max_segment/bulk_fallback_eager: lazy-segment bulking
    - resilience counters (docs/resilience.md): sentinel_checks/
      sentinel_nonfinite/sentinel_grad_norm_trips/sentinel_rollbacks,
      health_skipped_steps (sentinel skips + AMP overflow skips, one
      shared series), ckpt_saves/ckpt_restores/ckpt_restore_skipped,
      ckpt_async_saves/ckpt_async_waits/ckpt_async_failures (background
      checkpoint writer: launches, next-save barrier waits, dropped
      writes), faults_armed/faults_fired, watchdog_guards/stalls/
      crash_reports/rollbacks/peer_lost, watchdog_peer_recoveries (peer
      losses survived by mesh shrink), elastic_oom_events/shrinks/
      accum_steps, elastic_mesh_shrinks
    - serving counters (docs/serving.md): serving_requests/batches/
      batch_samples/padded_samples (pad waste), bucket hits/misses/
      compiles, shed_deadline/shed_overload, poisoned_batches,
      stalled_batches, queue_peak, p50/p99 request latency (us)
    - fleet counters (docs/serving.md "Fleet"): fleet_requests/retries/
      hedges/hedge_wins, fleet_breaker_opens/half_open_probes,
      fleet_probe_failures/replica_failures, fleet_restarts/drains,
      fleet_shed_overloaded/deadline_exceeded, fleet-level p50/p99
      latency (us) and the per-replica summary string
      fleet_replica_latency_us
    - dataloader_respawns: multiprocessing DataLoader workers respawned
      after dying mid-epoch (docs/resilience.md)
    - streaming-ingestion counters (docs/data.md): io_batches_streamed
      (host batches assembled by StreamBatchIter), io_records_corrupt
      (CRC-failed records skipped under policy=skip),
      io_prefetch_depth (DevicePrefetcher ring occupancy, last
      observed), io_stream_resumes (iterators rewound from a resume
      token)
    - capture counters (docs/capture.md): capture_steps/hits/misses,
      capture_retraces (recompiles of a captured program, each with a
      structured reason in the dispatch ring and capture.retrace_log()),
      capture_fallback_eager, aot_cache_hits/misses/stale/corrupt/
      writes/evictions (the persistent AOT compile cache)
    - int8 calibration counters (docs/quantization.md): calib_batches/
      calib_tensor_syncs (one device->host pull per monitored tensor per
      batch), calib_ms (wall-clock in the collectors),
      calib_tables_saved/loaded, calib_mismatches (stale table/model
      pairs rejected); serving_quantized_predictors/compiles above
    - observability counters (docs/observability.md): obs_spans/
      obs_spans_shipped (trace spans recorded locally / ingested from
      process replicas), obs_flight_events, obs_metric_flushes/
      obs_metric_samples (JSON-lines exporter), obs_dumps,
      perf_ledger_entries/perf_device_timings (perf attribution), and
      the alert engine's alert_evaluations/alert_transitions/
      alert_incidents_opened/alert_incidents_resolved
    - kernel-autotuning counters (docs/autotune.md): autotune_searches/
      autotune_candidates/autotune_rejected (measured schedule searches,
      candidates timed, candidates killed by the numerics gate) and
      autotune_table_hits/autotune_table_misses (kernel-builder schedule
      lookups answered by the table vs the defaults)

    The snapshot (and an optional ``reset=True``) runs under the
    profiler lock, so two concurrent callers — or a caller racing
    ``reset_dispatch_stats()`` — can never observe a torn snapshot
    mixing pre- and post-reset counters. ``lock_timeout`` (seconds)
    bounds the wait for that lock: on expiry the call degrades to an
    UNLOCKED best-effort snapshot (and skips any requested reset)
    instead of blocking — the watchdog's crash-report writer uses this,
    because the stalled thread it is reporting on may be wedged while
    holding the profiler lock, and forensics beat atomicity there.
    """
    from . import capture, engine, observability, resilience, serving, tune
    from .contrib import quantization
    from .gluon.data import dataloader
    from .io import stream
    from .ops import registry

    if lock_timeout is None:
        locked = _LOCK.acquire()
    else:
        locked = _LOCK.acquire(timeout=lock_timeout)
    try:
        stats = registry.dispatch_stats()
        stats.update(engine.bulk_stats())
        stats.update(resilience.stats())
        stats.update(serving.stats())
        stats.update(dataloader.stats())
        stats.update(stream.stats())
        stats.update(capture.stats())
        stats.update(quantization.stats())
        stats.update(observability.stats())
        stats.update(tune.stats())
        if reset and locked:
            _reset_dispatch_stats_locked()
    finally:
        if locked:
            _LOCK.release()
    return stats


def reset_dispatch_stats():
    """Zero all dispatch counters (registry + engine + resilience +
    serving + dataloader + stream + capture + quantization +
    observability + tune).
    Takes the profiler lock so a concurrent ``dispatch_stats()`` sees
    either the pre-reset or the post-reset world, never a mix."""
    with _LOCK:
        _reset_dispatch_stats_locked()


def _reset_dispatch_stats_locked():
    from . import capture, engine, observability, resilience, serving, tune
    from .contrib import quantization
    from .gluon.data import dataloader
    from .io import stream
    from .ops import registry

    registry.reset_dispatch_stats()
    for k in engine._STATS:
        engine._STATS[k] = 0
    resilience.reset_stats()
    serving.reset_stats()
    dataloader.reset_stats()
    stream.reset_stats()
    capture.reset_stats()
    quantization.reset_stats()
    observability.reset_stats()
    tune.reset_stats()


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate stats as a printable table (profiler.py:151), followed by
    the dispatch counter table (cache hits, donation, bulking)."""
    with _LOCK:
        agg = {}
        for name, _, dur, _cat in _EVENTS:
            tot, cnt = agg.get(name, (0.0, 0))
            agg[name] = (tot + dur, cnt + 1)
        if reset:
            _EVENTS.clear()
    rows = sorted(agg.items(), key=lambda kv: kv[1][0], reverse=not ascending)
    lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, (tot, cnt) in rows:
        lines.append(f"{name:<40}{cnt:>8}{tot * 1e3:>12.3f}"
                     f"{tot / cnt * 1e3:>12.3f}")
    lines.append("")
    lines.append(f"{'Dispatch counter':<40}{'Value':>12}")
    for name, value in sorted(dispatch_stats(reset=reset).items()):
        lines.append(f"{name:<40}{value:>12}")
    return "\n".join(lines)


class _Record:
    """Common base for profiler objects (Task/Frame/Event — profiler.py)."""

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None or _PAUSED:
            return
        dur = time.perf_counter() - self._t0
        with _LOCK:
            _EVENTS.append((self.name, self._t0, dur,
                            type(self).__name__.lower()))
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class Task(_Record):
    def __init__(self, name, domain=None):
        super().__init__(name)


class Frame(_Record):
    def __init__(self, name, domain=None):
        super().__init__(name)


class Event(_Record):
    pass


class Marker:
    """Instant marker (profiler.py Marker.mark)."""

    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        with _LOCK:
            _EVENTS.append((self.name, time.perf_counter(), 0.0, "marker"))


class Counter:
    """Named counter (profiler.py Counter)."""

    def __init__(self, name, domain=None, value=0):
        self.name = name
        set_value = value
        with _LOCK:
            _COUNTERS[name] = set_value

    def set_value(self, value):
        with _LOCK:
            _COUNTERS[self.name] = value

    def increment(self, delta=1):
        with _LOCK:
            _COUNTERS[self.name] = _COUNTERS.get(self.name, 0) + delta

    def decrement(self, delta=1):
        self.increment(-delta)


@contextlib.contextmanager
def scope(name="<unk>:", append_mode=False):
    """Profiler scope annotating jax ops (maps to jax named_scope so device
    events in the xplane trace carry the name)."""
    import jax

    ev = Event(name)
    ev.start()
    try:
        with jax.named_scope(name.rstrip(":")):
            yield
    finally:
        ev.stop()
