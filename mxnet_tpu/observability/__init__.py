"""mxnet_tpu.observability — the unified runtime-introspection layer.

The TensorFlow and MXNet systems papers both treat first-class runtime
introspection — per-step timelines, queue/latency telemetry, exportable
metrics — as a prerequisite for operating at production scale. This
package is that layer for the whole runtime (docs/observability.md):

- :mod:`trace` — structured span tracing. ``trace.span(name, **attrs)``
  opens one timed span (``perf_counter_ns``) under a thread-local
  trace context; spans nest, propagate across threads
  (``trace.context``) and across the serving fleet's process-replica
  pipe (span records ship back with the reply), and land in a bounded
  ring. One serving request or one training step yields a complete
  parent/child timeline under one trace id. Off by default
  (``MXNET_TPU_OBS_TRACE``) with a near-zero disabled cost — the
  ``tools/obs_bench.py`` gate holds tracing to <= 2% step overhead
  enabled and ~0 disabled.
- :mod:`metrics` — a typed metrics registry (counters / gauges /
  histograms with labels) generalizing the flat ``_STATS`` counter
  dicts, with a ring-buffered time series and two exporters: JSON-lines
  (``MXNET_TPU_METRICS_FILE``, flushed on a cadence) and Prometheus
  text exposition (``metrics.render_prometheus()`` + an optional
  stdlib-http endpoint). Fleet SLO series (per-model deadline hit-rate,
  shed rate, p50/p99, breaker state) are derived automatically.
- :mod:`flight` — the always-on flight recorder: one bounded
  chronological event log unifying span ends, fault injections,
  watchdog stalls, capture retrace reasons, checkpoint publishes and
  fleet state transitions. Watchdog crash reports embed its tail;
  ``observability.dump()`` / ``tools/obs_dump.py`` dump it on demand.
- :mod:`perf` — performance attribution: a per-executable ledger (XLA
  cost/memory analysis + compile time, keyed by the AOT fingerprint),
  opt-in dependency-chained device timing
  (``MXNET_TPU_OBS_DEVICE_TIME``), and derived MFU / roofline gauges;
  ``tools/perf_gate.py`` gates it against a committed baseline.
- :mod:`alerts` — the interpretation layer on top of all of the above:
  declarative alert rules (multi-window SLO burn rate, live threshold
  probes, statistical anomaly detectors) evaluated on the exporter
  cadence, with per-rule FIRING/RESOLVED state, hold/cooldown flap
  suppression, ``alert`` flight events, and correlated
  :class:`~alerts.Incident` reports (evidence window + flight slice +
  exemplar span trees + perf deltas + fleet states);
  ``tools/obs_alerts.py`` is the CLI.
- :mod:`traceview` — Chrome-trace timeline export:
  ``traceview.to_chrome_trace()`` converts span records (fleet trees
  included, pid/tid mapped from replica/thread identity) to Trace
  Event Format JSON for Perfetto / ``chrome://tracing``;
  ``tools/trace_export.py`` is the CLI and incidents embed their
  exemplars' timeline.

Everything here is stdlib-only at import so the hot paths (trainer,
registry, serving) can instrument without dragging in jax.
"""
from __future__ import annotations

# Counters are defined BEFORE the submodule imports at the bottom so
# trace.py / metrics.py / flight.py can `from . import _STATS` during
# package init (the serving-package pattern; RD002 resolves it).
_STATS = {
    "obs_spans": 0,            # span records placed in the local ring
    "obs_spans_shipped": 0,    # span records ingested from replica pipes
    "obs_flight_events": 0,    # flight-recorder events recorded
    "obs_metric_flushes": 0,   # JSON-lines exporter flushes
    "obs_metric_samples": 0,   # time-series ring samples taken
    "obs_dumps": 0,            # observability.dump() calls
    "perf_ledger_entries": 0,  # executables attributed in the perf ledger
    "perf_device_timings": 0,  # dependency-chained timed executions
    "alert_evaluations": 0,          # alert-engine evaluation rounds
    "alert_transitions": 0,          # FIRING/RESOLVED state transitions
    "alert_incidents_opened": 0,     # incidents assembled on FIRING
    "alert_incidents_resolved": 0,   # incidents closed on RESOLVED
    "numerics_samples": 0,           # in-graph numerics samples pulled
    "numerics_nonfinite_steps": 0,   # steps the fused finite flag failed
    "numerics_snapshots": 0,         # numerics snapshots published
    "numerics_halts": 0,             # halt-policy divergence raises
}


def stats():
    """All observability counters as one flat dict (merged into
    ``profiler.dispatch_stats()``)."""
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


from . import trace  # noqa: E402
from . import metrics  # noqa: E402
from . import flight  # noqa: E402
from . import numerics  # noqa: E402
from . import perf  # noqa: E402
from . import alerts  # noqa: E402
from . import traceview  # noqa: E402

# operator story: exporting metrics needs ONLY the env knob — with
# MXNET_TPU_METRICS_FILE set, the background JSON-lines flusher arms
# itself the moment the runtime imports this layer (no-op otherwise)
metrics.maybe_start_flusher()


def dump(limit=None):
    """One self-describing snapshot of the whole layer: the flight
    recorder (chronological, oldest first), the ended-span ring, the
    metrics registry and its time series, and the runtime counter dict.
    This is the payload ``tools/obs_dump.py`` prints and the on-demand
    counterpart of the crash report's embedded flight tail."""
    from .. import profiler

    _STATS["obs_dumps"] += 1
    try:
        counters = profiler.dispatch_stats()
    except Exception:
        counters = {}
    return {
        "schema_version": 2,
        "flight": flight.snapshot(limit=limit),
        "spans": trace.spans(),
        "metrics": metrics.snapshot(),
        "series": metrics.series(),
        "perf": perf.snapshot(),
        "numerics": numerics.snapshot_state(),
        "alerts": alerts.snapshot(),
        "incidents": alerts.incidents(),
        "counters": counters,
    }


__all__ = ["trace", "metrics", "flight", "numerics", "perf", "alerts",
           "traceview", "dump", "stats", "reset_stats"]
