"""Watchtower: SLO burn-rate alerting, anomaly detection, incidents.

PRs 10–11 built the raw signal plane — span trees, typed metrics with
derived SLO gauges, the flight recorder, the per-executable perf
ledger — but nothing *interprets* it: an operator watching a fleet for
millions of users still has to eyeball ``/metrics`` to notice an SLO
burn. This module turns the telemetry from inspectable into
diagnostic (docs/observability.md, "Alerting & incidents"):

- **Alert rules** — a declarative registry of rules evaluated on the
  existing exporter cadence (every ``metrics.update_derived()`` call,
  i.e. every snapshot / Prometheus render / JSON-lines flush):
  multi-window SLO **burn rate** over the fleet counters (deadline
  hit-rate and shed rate, fast+slow windows — the SRE burn-rate
  shape), live **threshold** probes (circuit breaker open,
  healthy-replica floor, input-stall ceiling), and **statistical
  anomaly detectors** (rolling median/MAD drift on step time, EWMA
  device-time / MFU regression against the perf ledger, grad-norm /
  health-skip spike).
- **Per-rule state machine** — ``OK -> PENDING -> FIRING -> OK`` with
  ``hold_s`` (a breach must persist before FIRING) and ``cooldown_s``
  (conditions must stay clean before RESOLVED) to suppress flapping;
  every FIRING/RESOLVED transition lands one ``alert`` event in the
  flight recorder.
- **Incidents** — a FIRING transition assembles one structured,
  JSON-serializable :class:`Incident`: the rule's evidence window, the
  flight-recorder slice covering it, the K slowest matching span trees
  as exemplars (plus their Chrome-trace timeline via
  :mod:`traceview`), perf-ledger entries for implicated executables,
  and the fleet's replica/breaker states. Surfaced by
  ``observability.dump()["incidents"]``, ``tools/obs_alerts.py``, the
  ``/obs`` endpoint, and embedded in watchdog crash reports next to
  the flight tail.

Disabled (``MXNET_TPU_ALERTS=0`` or :func:`set_enabled`), the
evaluation site is one global check — the tracing no-op discipline —
and since evaluation rides the exporter cadence (never the step or
request hot path), the ``tools/obs_bench.py`` <=2% overhead gate is
untouched by construction. Stdlib-only at import.
"""
from __future__ import annotations

import itertools
import os
import sys
import threading
import time

from collections import deque

from . import _STATS
from . import flight as _flight
from . import metrics as _metrics
from . import perf as _perf

__all__ = ["AlertRule", "BurnRateRule", "ThresholdRule",
           "StepTimeDriftRule", "PerfLedgerDropRule", "CounterSpikeRule",
           "ALERT_RULE_IDS", "register_rule", "unregister_rule", "rules",
           "get_rule", "evaluate", "maybe_evaluate", "enabled",
           "set_enabled", "incidents", "open_incidents", "snapshot",
           "reset", "Incident"]

_LOCK = threading.Lock()
_RULES: dict = {}
_HISTORY: deque = deque(maxlen=512)   # evaluation observations (windows)
_INCIDENTS: deque = None              # sized below from the env knob
_INCIDENT_IDS = itertools.count(1)
# REAL monotonic time of the last exporter-cadence evaluation. Kept
# separate from any caller-supplied evaluation clock: rate-limiting
# against a synthetic drill clock would let one large `now` suppress
# real exporter ticks until the host clock caught up.
_LAST_TICK = None

_ENABLED = os.environ.get("MXNET_TPU_ALERTS", "").strip() not in (
    "0", "false", "off", "no")


def _env_float(name, default):
    try:
        raw = os.environ.get(name, "").strip()
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name, default):
    try:
        raw = os.environ.get(name, "").strip()
        return int(raw) if raw else default
    except ValueError:
        return default


_INCIDENTS = deque(maxlen=max(1, _env_int("MXNET_TPU_ALERT_INCIDENTS", 64)))

# THE rule-id registry (graftlint RD006: every id must be documented
# under docs/ AND exercised by tests/test_alerts.py or the chaos
# harness; a closure test pins the registered defaults to this tuple).
ALERT_RULE_IDS = (
    "slo_deadline_burn",      # fleet deadline-miss burn rate, 2 windows
    "slo_shed_burn",          # fleet overload-shed burn rate, 2 windows
    "fleet_breaker_open",     # any live replica's circuit breaker open
    "fleet_healthy_floor",    # a model's HEALTHY replicas under the floor
    "input_stall_high",       # input-stall fraction over its ceiling
    "step_time_drift",        # step time outside median + k*MAD
    "perf_device_regression", # ledger device_ms/MFU off its own EWMA
    "health_skip_spike",      # sentinel skips/grad-norm trips spiking
    "numerics_nonfinite",     # in-graph tap: non-finite gradient onset
    "numerics_grad_explosion",# in-graph tap: grad norm off median+k*MAD
    "numerics_dead_layer",    # in-graph tap: a layer stopped training
    "decode_ttft_burn",       # decode TTFT SLO-miss burn rate, 2 windows
    "pod_host_down",          # a pod host's heartbeat/liveness lost
    "sdc_detected",           # integrity layer caught silent corruption
)


def enabled():
    return _ENABLED


def set_enabled(flag):
    """Turn alert evaluation on/off at runtime (the post-import
    counterpart of ``MXNET_TPU_ALERTS``); returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


# ------------------------------------------------------------------ context

class _EvalContext:
    """Everything one evaluation round hands the rules: the clock, the
    fresh observation (windowed counters), the history ring, and lazy
    accessors for the live sources (fleets, span ring, perf ledger)."""

    def __init__(self, now, obs, history, input_stall=None):
        self.now = now
        self.obs = obs
        self.history = history
        # input-stall fraction already derived this tick (update_derived
        # passes its own update_input_stall() result so the gauge and
        # the rule judge the same number, once); None = derive on demand
        self.input_stall = input_stall

    def windowed(self, group, key, window_s):
        """Delta of ``history[...][group][key]`` over the trailing
        ``window_s`` seconds (the newest sample at or before
        ``now - window_s``; the oldest sample when the history is
        younger than the window). Returns 0 with fewer than 2 samples."""
        cur = self.obs.get(group, {}).get(key, 0)
        base = None
        for h in self.history:
            if h is self.obs:
                continue
            if h["now"] <= self.now - window_s:
                base = h
            else:
                break
        if base is None:
            for h in self.history:
                if h is not self.obs:
                    base = h
                    break
        if base is None:
            return 0
        return cur - base.get(group, {}).get(key, 0)

    def seq_at(self, window_s):
        """Flight-recorder bookmark at (or before) ``now - window_s`` —
        the start of an incident's evidence slice."""
        seq = None
        for h in self.history:
            seq = h["seq"] if seq is None else seq
            if h["now"] <= self.now - window_s:
                seq = h["seq"]
            else:
                break
        return seq or 0

    def fleets(self):
        try:
            import sys

            serving = sys.modules.get("mxnet_tpu.serving")
            if serving is None:
                return []
            return serving._live_fleets()
        except Exception:
            return []


def _slo_counters():
    """The fleet SLO counter triple the burn-rate rules consume — the
    same ``slo_burn``-hook-applied view ``metrics.update_slo`` derives
    its gauges from (``metrics.slo_counters``), so the drill's injected
    burn reaches gauges and alert windows identically. Empty until the
    serving layer has actually been imported (a light process must not
    drag it in just to evaluate rules)."""
    import sys

    if sys.modules.get("mxnet_tpu.serving") is None:
        return {}
    try:
        return _metrics.slo_counters()
    except Exception:
        return {}


def _decode_counters():
    """The decode SLO counter pair (admitted sequences, TTFT misses)
    the decode burn-rate rule windows — ``metrics.decode_counters``,
    which is itself empty until the serving layer is imported."""
    try:
        return _metrics.decode_counters()
    except Exception:
        return {}


def _health_counters():
    try:
        import sys

        sentinel = sys.modules.get("mxnet_tpu.resilience.sentinel")
        if sentinel is None:
            return {}
        return {
            "health_skipped_steps": sentinel._STATS["health_skipped_steps"],
            "sentinel_grad_norm_trips":
                sentinel._STATS["sentinel_grad_norm_trips"],
        }
    except Exception:
        return {}


def _integrity_counters():
    """The SDC-detection counters the ``sdc_detected`` rule windows —
    pulled lazily so importing observability never drags the
    resilience layer in (same model as ``_health_counters``)."""
    try:
        import sys

        integrity = sys.modules.get("mxnet_tpu.resilience.integrity")
        if integrity is None:
            return {}
        st = integrity._STATS
        return {
            "integrity_audit_mismatches": st["integrity_audit_mismatches"],
            "integrity_selftest_failures":
                st["integrity_selftest_failures"],
            "integrity_serving_failures": st["integrity_serving_failures"],
            "integrity_ckpt_mismatches": st["integrity_ckpt_mismatches"],
        }
    except Exception:
        return {}


# -------------------------------------------------------------------- rules

class AlertRule:
    """One declarative rule. Subclasses implement :meth:`check` ->
    ``(breached, evidence)``; the engine owns the OK/PENDING/FIRING
    state machine, hold/cooldown timing, flight events and incident
    assembly. ``span_names`` hints which span trees make good incident
    exemplars; ``window_s`` sizes the incident's evidence slice."""

    def __init__(self, id, description="", severity="page", hold_s=None,
                 cooldown_s=None, span_names=(), window_s=None):
        self.id = str(id)
        self.description = description
        self.severity = severity
        self.hold_s = _env_float("MXNET_TPU_ALERT_HOLD_S", 0.0) \
            if hold_s is None else float(hold_s)
        self.cooldown_s = _env_float("MXNET_TPU_ALERT_COOLDOWN_S", 60.0) \
            if cooldown_s is None else float(cooldown_s)
        self.span_names = tuple(span_names)
        self.window_s = float(window_s) if window_s is not None else \
            _env_float("MXNET_TPU_ALERT_BURN_SLOW_S", 300.0)
        # state machine (engine-owned, under the module lock)
        self.state = "OK"
        self.pending_since = None
        self.last_breach = None
        self.incident_id = None
        self.last_evidence = None

    def check(self, ctx):  # pragma: no cover - abstract
        raise NotImplementedError

    def describe(self):
        return {"id": self.id, "severity": self.severity,
                "state": self.state, "hold_s": self.hold_s,
                "cooldown_s": self.cooldown_s,
                "description": self.description}


class BurnRateRule(AlertRule):
    """Multi-window SLO burn rate (the SRE alerting shape): with an
    objective of e.g. 99% deadline hit-rate the error budget is 1%,
    and ``burn = windowed_error_rate / budget``. The rule fires only
    when BOTH the fast and the slow window burn at >= ``factor``×
    budget — the fast window gives detection latency, the slow window
    keeps a one-sample blip from paging."""

    def __init__(self, id, num_key, den_key, objective=None, fast_s=None,
                 slow_s=None, factor=None, group="slo", **kw):
        self.num_key = num_key
        self.den_key = den_key
        self.group = group  # observation group the windows read
        self.objective = _env_float("MXNET_TPU_ALERT_SLO_TARGET", 0.99) \
            if objective is None else float(objective)
        self.fast_s = _env_float("MXNET_TPU_ALERT_BURN_FAST_S", 60.0) \
            if fast_s is None else float(fast_s)
        self.slow_s = _env_float("MXNET_TPU_ALERT_BURN_SLOW_S", 300.0) \
            if slow_s is None else float(slow_s)
        self.factor = _env_float("MXNET_TPU_ALERT_BURN_FACTOR", 4.0) \
            if factor is None else float(factor)
        kw.setdefault("span_names", ("serve.request",))
        kw.setdefault("window_s", self.slow_s)
        super().__init__(id, **kw)

    def _burn(self, ctx, window_s):
        num = ctx.windowed(self.group, self.num_key, window_s)
        den = ctx.windowed(self.group, self.den_key, window_s)
        if den <= 0:
            return 0.0, num, den
        budget = max(1e-9, 1.0 - self.objective)
        return (num / den) / budget, num, den

    def check(self, ctx):
        fast, fnum, fden = self._burn(ctx, self.fast_s)
        slow, snum, sden = self._burn(ctx, self.slow_s)
        breached = fast >= self.factor and slow >= self.factor
        evidence = {
            "objective": self.objective, "burn_factor": self.factor,
            "windows": {
                "fast": {"window_s": self.fast_s, "burn": round(fast, 3),
                         self.num_key: fnum, self.den_key: fden},
                "slow": {"window_s": self.slow_s, "burn": round(slow, 3),
                         self.num_key: snum, self.den_key: sden},
            },
        }
        return breached, evidence


class ThresholdRule(AlertRule):
    """Live threshold probe: ``value_fn(ctx) -> (value, detail)`` read
    against ``threshold`` with comparator ``op`` (one of ``>`` ``>=``
    ``<`` ``<=``). ``value=None`` means no data — never a breach."""

    _OPS = {">": lambda v, t: v > t, ">=": lambda v, t: v >= t,
            "<": lambda v, t: v < t, "<=": lambda v, t: v <= t}

    def __init__(self, id, value_fn, op, threshold, **kw):
        super().__init__(id, **kw)
        self.value_fn = value_fn
        self.op = op
        self.threshold = threshold

    def check(self, ctx):
        value, detail = self.value_fn(ctx)
        if value is None:
            return False, None
        breached = self._OPS[self.op](value, self.threshold)
        evidence = {"value": value, "op": self.op,
                    "threshold": self.threshold}
        if detail:
            evidence.update(detail)
        return breached, evidence


class StepTimeDriftRule(AlertRule):
    """Rolling median/MAD drift detector on training-step wall time:
    ingests every new step-root span duration from the trace ring
    (``MXNET_TPU_OBS_TRACE`` must be on for it to have data), keeps a
    window of recent durations, and breaches when a new step lands
    beyond ``median + k * 1.4826*MAD`` with at least ``min_n`` clean
    samples banked. Outliers are NOT folded into the baseline, so a
    sustained anomaly keeps breaching instead of normalizing itself.
    The ``step_time_anomaly`` fault hook inflates each ingested
    duration — the chaos drill's injection point."""

    STEP_ROOTS = ("train.step", "train.sharded_step",
                  "train.captured_step")

    def __init__(self, id, k=None, min_n=8, window_n=64, **kw):
        kw.setdefault("span_names", self.STEP_ROOTS)
        super().__init__(id, **kw)
        self.k = _env_float("MXNET_TPU_ALERT_MAD_K", 6.0) \
            if k is None else float(k)
        self.min_n = int(min_n)
        self.durs = deque(maxlen=int(window_n))
        self.last_t0 = 0

    def _new_durations(self):
        from . import trace as _trace

        try:
            from ..resilience import faults
            inflate = faults.maybe_step_time_anomaly
        except Exception:
            def inflate(d):
                return d
        out = []
        high = self.last_t0
        for s in _trace.spans():
            if s["name"] not in self.STEP_ROOTS or \
                    s["t0_ns"] <= self.last_t0:
                continue
            high = max(high, s["t0_ns"])
            # numerics-sampled steps pay the telemetry variant + host
            # pull by DESIGN (observability.numerics): a configured
            # sampling cadence is periodic and expected, not drift —
            # they neither breach nor bank into the baseline
            if (s.get("attrs") or {}).get("numerics_sampled"):
                continue
            out.append(inflate(s["dur_ns"]))
        self.last_t0 = high
        return out

    @staticmethod
    def _median(values):
        vals = sorted(values)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def check(self, ctx):
        worst = None
        med = mad = None
        for dur in self._new_durations():
            if len(self.durs) >= self.min_n:
                med = self._median(self.durs)
                mad = self._median([abs(d - med) for d in self.durs])
                sigma = 1.4826 * mad
                # two floors under the envelope: the spread floor (5%
                # of the median) keeps a perfectly steady loop (MAD ~0)
                # from paging on scheduler dust, and the hard 2x floor
                # means only at least a DOUBLING of step time can ever
                # page — one CI scheduling blip is not an anomaly
                limit = max(med + self.k * max(sigma, 0.05 * med),
                            2.0 * med)
                if dur > limit:
                    if worst is None or dur > worst["dur_ns"]:
                        worst = {"dur_ns": dur, "limit_ns": limit,
                                 "median_ns": med, "mad_ns": mad}
                    continue  # outliers stay out of the baseline
            self.durs.append(dur)
        if worst is None:
            return False, None
        keys = sorted(k for k, e in _perf.ledger().items()
                      if not str(e.get("label", "")).startswith("serving"))
        worst.update(k=self.k, n=len(self.durs), ledger_keys=keys)
        return True, worst


class PerfLedgerDropRule(AlertRule):
    """Device-time / MFU regression against the perf ledger's own EWMA
    (``MXNET_TPU_OBS_DEVICE_TIME`` feeds it): the rule banks a slow
    EWMA baseline per ledger key and breaches when the live
    ``device_ms`` rises (or ``mfu`` falls) beyond ``tolerance`` of it."""

    def __init__(self, id, tolerance=None, min_calls=5, alpha=0.05, **kw):
        kw.setdefault("span_names", ("perf.device_execute",))
        super().__init__(id, **kw)
        self.tolerance = _env_float("MXNET_TPU_ALERT_PERF_TOL", 0.5) \
            if tolerance is None else float(tolerance)
        self.min_calls = int(min_calls)
        self.alpha = float(alpha)
        self.baselines: dict = {}   # key -> {device_ms, mfu}

    def check(self, ctx):
        regressed = {}
        live = set()
        for key, e in _perf.device_timed_entries(self.min_calls).items():
            live.add(key)
            base = self.baselines.get(key)
            if base is None:
                self.baselines[key] = {"device_ms": e["device_ms"],
                                       "mfu": e.get("mfu")}
                continue
            slow = e["device_ms"] > base["device_ms"] * (1 + self.tolerance)
            mfu_drop = (e.get("mfu") is not None
                        and base.get("mfu")
                        and e["mfu"] < base["mfu"] * (1 - self.tolerance))
            if slow or mfu_drop:
                regressed[key] = {
                    "device_ms": e["device_ms"],
                    "baseline_device_ms": round(base["device_ms"], 4),
                    "mfu": e.get("mfu"), "baseline_mfu": base.get("mfu"),
                    "tolerance": self.tolerance,
                }
                continue  # a regressed sample must not drag the baseline
            base["device_ms"] += self.alpha * (e["device_ms"]
                                               - base["device_ms"])
            if e.get("mfu") is not None:
                prev = base.get("mfu") or e["mfu"]
                base["mfu"] = prev + self.alpha * (e["mfu"] - prev)
        for key in list(self.baselines):
            if key not in live:
                del self.baselines[key]  # re-fingerprinted program
        if not regressed:
            return False, None
        return True, {"regressed": regressed,
                      "ledger_keys": sorted(regressed)}


class CounterSpikeRule(AlertRule):
    """Windowed counter spike: the summed delta of ``keys`` (history
    group ``group``) over the fast window reaching ``threshold``."""

    def __init__(self, id, group, keys, threshold=None, window_s=None,
                 **kw):
        fast = _env_float("MXNET_TPU_ALERT_BURN_FAST_S", 60.0)
        # the detection window IS the evidence window (base window_s)
        kw.setdefault("window_s", fast if window_s is None else window_s)
        super().__init__(id, **kw)
        self.group = group
        self.keys = tuple(keys)
        self.threshold = _env_float("MXNET_TPU_ALERT_SKIP_SPIKE", 3.0) \
            if threshold is None else float(threshold)

    def check(self, ctx):
        per_key = {k: ctx.windowed(self.group, k, self.window_s)
                   for k in self.keys}
        total = sum(per_key.values())
        if total < self.threshold:
            return False, None
        return True, {"window_s": self.window_s, "total": total,
                      "threshold": self.threshold, "by_counter": per_key}


# -------------------------------------------------------- threshold probes

def _probe_breakers(ctx):
    open_cells = []
    saw_fleet = False
    for fleet in ctx.fleets():
        try:
            if getattr(fleet, "_closed", False):
                continue
            for model in fleet.models():
                for r in fleet._sup.replicas(model):
                    saw_fleet = True
                    if r.breaker.is_open:
                        open_cells.append(f"{model}/{r.rid}")
        except Exception:
            continue
    if not saw_fleet:
        return None, None
    return len(open_cells), {"open": sorted(open_cells)}


def _probe_healthy_floor(ctx):
    worst = None
    detail = {}
    for fleet in ctx.fleets():
        try:
            # a close()d fleet lingers in the weakref registry until GC;
            # its replicas are all DEAD by operator intent (shutdown, not
            # sickness) and must never open a healthy-floor incident
            if getattr(fleet, "_closed", False):
                continue
            for model in fleet.models():
                # a replica draining for SCALE left by operator intent,
                # not sickness: it is no longer a fleet member for floor
                # purposes and must never open a healthy-floor incident
                replicas = [r for r in fleet._sup.replicas(model)
                            if not getattr(r, "scale_drain", False)]
                if not replicas:
                    continue
                healthy = sum(1 for r in replicas if r.state == "HEALTHY")
                detail[model] = healthy
                worst = healthy if worst is None else min(worst, healthy)
        except Exception:
            continue
    if worst is None:
        return None, None
    return worst, {"healthy_by_model": detail}


def _probe_input_stall(ctx):
    value = (ctx.input_stall if ctx.input_stall is not None
             else _metrics.update_input_stall())
    # evidence names WHERE the starving loop's streaming iterators sat
    # (epoch + global cursor, io/stream.py). sys.modules lookup, not an
    # import: when the stream module was never loaded there are no live
    # iterators, and the alert path must not drag the io package in.
    detail = None
    stream_mod = sys.modules.get("mxnet_tpu.io.stream")
    if stream_mod is not None:
        try:
            positions = stream_mod.live_positions()
        except Exception:
            positions = []
        if positions:
            detail = {"stream_positions": positions}
    return value, detail


def _probe_pod_hosts(ctx):
    """Dead pod hosts per the watchdog's host-domain liveness tracker.
    None (no data) until this process configures a pod — a single-host
    run must never evaluate, let alone fire, a host-down alert. The
    sticky dead set keeps the incident FIRING until re-admission
    (``watchdog.configure_pod`` / ``reset_hosts``) resolves it."""
    watchdog = sys.modules.get("mxnet_tpu.resilience.watchdog")
    if watchdog is None:
        return None, None
    snap = watchdog.pod_snapshot()
    if not snap.get("configured"):
        return None, None
    dead = sorted(snap.get("dead_hosts") or ())
    return len(dead), {"dead_hosts": dead,
                       "num_hosts": snap.get("num_hosts"),
                       "coordinator": snap.get("coordinator")}


def _probe_numerics(cond_name):
    """Threshold probe over one in-graph numerics divergence condition
    (``observability.numerics``): the tap evaluates the detector on its
    own sampling cadence and writes the automatic numerics snapshot at
    activation; the rule lifts that state — evidence window, offending
    rows, snapshot path — into a correlated Incident. ``None`` until a
    tap has ever judged the condition (rule stays inert in untapped
    processes)."""

    def probe(ctx):
        from . import numerics

        cond = numerics.condition(cond_name)
        if cond is None:
            return None, None
        detail = {"since_step": cond.get("since_step"),
                  "snapshot": cond.get("snapshot")}
        detail.update(cond.get("evidence") or {})
        return (1 if cond.get("active") else 0), detail

    return probe


def _default_rules():
    floor = _env_float("MXNET_TPU_ALERT_HEALTHY_FLOOR", 1.0)
    stall_max = _env_float("MXNET_TPU_ALERT_STALL_MAX", 0.5)
    return (
        BurnRateRule(
            "slo_deadline_burn", "fleet_deadline_exceeded",
            "fleet_requests",
            description="fleet deadline-miss rate burning the SLO error "
                        "budget in both the fast and slow window"),
        BurnRateRule(
            "slo_shed_burn", "fleet_shed_overloaded", "fleet_requests",
            description="fleet overload-shed rate burning the SLO error "
                        "budget in both the fast and slow window"),
        ThresholdRule(
            "fleet_breaker_open", _probe_breakers, ">=", 1,
            span_names=("serve.request",),
            description="at least one live replica's circuit breaker is "
                        "open (requests are being rerouted around it)"),
        ThresholdRule(
            "fleet_healthy_floor", _probe_healthy_floor, "<", floor,
            span_names=("serve.request",),
            description="a served model has fewer HEALTHY replicas than "
                        "the configured floor"),
        ThresholdRule(
            "input_stall_high", _probe_input_stall, ">", stall_max,
            span_names=("step.data_wait",),
            description="the training loop is input-bound: "
                        "mxnet_tpu_input_stall_fraction over its ceiling"),
        StepTimeDriftRule(
            "step_time_drift",
            description="training-step wall time drifted outside "
                        "median + k*MAD of its recent history"),
        PerfLedgerDropRule(
            "perf_device_regression",
            description="a ledgered executable's EWMA device time rose "
                        "(or its MFU fell) beyond tolerance of its own "
                        "baseline"),
        CounterSpikeRule(
            "health_skip_spike", "health",
            ("health_skipped_steps", "sentinel_grad_norm_trips"),
            description="HealthSentinel skips / grad-norm trips spiking "
                        "inside one fast window"),
        ThresholdRule(
            "numerics_nonfinite", _probe_numerics("nonfinite"), ">=", 1,
            span_names=("train.captured_step",),
            description="the captured step's in-graph numerics tap saw "
                        "a non-finite gradient (NaN/Inf onset); a "
                        "numerics snapshot was published for "
                        "tools/numerics_bisect.py"),
        ThresholdRule(
            "numerics_grad_explosion", _probe_numerics("grad_explosion"),
            ">=", 1, span_names=("train.captured_step",),
            description="the global gradient norm exploded outside "
                        "median + k*MAD of its own clean history "
                        "(in-graph numerics tap)"),
        ThresholdRule(
            "numerics_dead_layer", _probe_numerics("dead_layer"), ">=", 1,
            span_names=("train.captured_step",),
            description="a layer's gradients stayed ~0 or fully "
                        "fp16-underflowed for N consecutive samples "
                        "while the rest of the net kept training"),
        BurnRateRule(
            "decode_ttft_burn", "decode_ttft_misses", "decode_sequences",
            group="decode", span_names=("decode.prefill", "decode.admit"),
            description="decode time-to-first-token SLO misses burning "
                        "the error budget in both the fast and slow "
                        "window (TTFT over MXNET_TPU_DECODE_TTFT_SLO_MS "
                        "at admission)"),
        ThresholdRule(
            "pod_host_down", _probe_pod_hosts, ">=", 1,
            description="a pod host failure domain is dead: the "
                        "watchdog's liveness layer (heartbeats, pid "
                        "checks, stall blame) marked at least one host "
                        "rank dead; sticky until re-admission"),
        CounterSpikeRule(
            "sdc_detected", "integrity",
            ("integrity_audit_mismatches", "integrity_selftest_failures",
             "integrity_serving_failures", "integrity_ckpt_mismatches"),
            threshold=1,
            description="silent data corruption caught: a shadow replay "
                        "audit, device self-test, serving golden-query "
                        "check or checkpoint manifest fingerprint "
                        "mismatched inside one fast window"),
    )


def register_rule(rule):
    """Register (or replace) one rule; returns it. Default rules are
    registered at import — :func:`reset` restores exactly that set."""
    with _LOCK:
        _RULES[rule.id] = rule
    return rule


def unregister_rule(rule_id):
    with _LOCK:
        return _RULES.pop(rule_id, None) is not None


def rules():
    with _LOCK:
        return dict(_RULES)


def get_rule(rule_id):
    with _LOCK:
        return _RULES.get(rule_id)


# ---------------------------------------------------------------- incidents

class Incident(dict):
    """One correlated diagnosis bundle (a plain dict subclass so it
    JSON-serializes as-is). Keys: ``id``, ``rule``, ``severity``,
    ``description``, ``status`` (open|resolved), ``opened_t`` /
    ``opened_now`` / ``resolved_t`` / ``resolved_now``, ``evidence``
    (the rule's window math), ``flight`` (the recorder slice covering
    the evidence window), ``exemplars`` (the K slowest matching span
    trees), ``chrome_trace`` (their Trace Event Format timeline),
    ``perf`` (ledger entries for implicated executables), and
    ``fleet`` (replica/breaker states at open time)."""


def _exemplar_trees(span_names, k):
    """The ``k`` slowest root spans matching ``span_names`` (all roots
    when empty), each expanded to its full tree (every ring record
    sharing the trace id). Trees are slowest-first and each tree lists
    its ROOT record first (descendants follow in ring order — children
    end before their parents, so raw ring order buries the root)."""
    from . import trace as _trace

    recs = _trace.spans()
    roots = _trace.roots(names=span_names)
    roots.sort(key=lambda r: r["dur_ns"], reverse=True)
    trees = []
    for root in roots[:k]:
        rest = [r for r in recs
                if r["trace"] == root["trace"] and r is not root]
        trees.append([root] + rest)
    return trees


def _fleet_states():
    out = []
    try:
        import sys

        serving = sys.modules.get("mxnet_tpu.serving")
        if serving is None:
            return out
        for fleet in serving._live_fleets():
            try:
                for model in fleet.models():
                    for r in fleet._sup.replicas(model):
                        out.append({"model": model, "replica": r.rid,
                                    "state": getattr(r, "display_state",
                                                     r.state),
                                    "breaker_open": bool(r.breaker.is_open)})
            except Exception:
                continue
    except Exception:
        pass
    return out


def _open_incident(rule, evidence, ctx):
    since = ctx.seq_at(rule.window_s)
    flight_slice = _flight.events(since_seq=since)
    k = max(1, _env_int("MXNET_TPU_ALERT_EXEMPLARS", 3))
    trees = _exemplar_trees(rule.span_names, k)
    inc = Incident(
        id=f"inc-{next(_INCIDENT_IDS)}",
        rule=rule.id,
        severity=rule.severity,
        description=rule.description,
        status="open",
        opened_t=time.time(),
        opened_now=ctx.now,
        resolved_t=None,
        resolved_now=None,
        evidence=evidence or {},
        flight=flight_slice,
        exemplars=trees,
        perf={key: e for key, e in _perf.ledger().items()
              if key in set((evidence or {}).get("ledger_keys", ()))},
        fleet=_fleet_states(),
    )
    if os.environ.get("MXNET_TPU_ALERT_CHROME_TRACE", "").strip() not in (
            "0", "false", "off", "no"):
        try:
            from . import traceview

            inc["chrome_trace"] = traceview.to_chrome_trace(
                [r for tree in trees for r in tree])
        except Exception:
            inc["chrome_trace"] = None
    with _LOCK:
        _INCIDENTS.append(inc)
    _STATS["alert_incidents_opened"] += 1
    return inc


def incidents(status=None, limit=None):
    """Recorded incidents, oldest first; optionally filtered by
    ``status`` (``open``/``resolved``) and truncated to the newest
    ``limit``. Entries are live dicts of a bounded ring — treat as
    read-only snapshots."""
    with _LOCK:
        out = list(_INCIDENTS)
    if status is not None:
        out = [i for i in out if i["status"] == status]
    if limit is not None and limit >= 0:
        out = out[-limit:] if limit else []  # -0: would slice ALL
    return out


def open_incidents():
    return incidents(status="open")


# ------------------------------------------------------------------- engine

def _advance(rule, breached, evidence, ctx):
    """One state-machine step for one rule; returns a transition string
    (``FIRING``/``RESOLVED``) or None. Runs outside the module lock
    (incident assembly reads other subsystems); per-rule state is only
    touched from the engine, which is serialized by ``_EVAL_LOCK``."""
    now = ctx.now
    if breached:
        rule.last_breach = now
        rule.last_evidence = evidence
        if rule.state == "OK":
            rule.pending_since = now
            rule.state = "PENDING"
        if rule.state == "PENDING" and \
                now - rule.pending_since >= rule.hold_s:
            rule.state = "FIRING"
            inc = _open_incident(rule, evidence, ctx)
            rule.incident_id = inc["id"]
            _flight.record("alert", rule=rule.id, state="FIRING",
                           severity=rule.severity, incident=inc["id"])
            _STATS["alert_transitions"] += 1
            return "FIRING"
        return None
    if rule.state == "PENDING":
        rule.state = "OK"
        rule.pending_since = None
        return None
    if rule.state == "FIRING" and \
            now - (rule.last_breach or now) >= rule.cooldown_s:
        rule.state = "OK"
        rule.pending_since = None
        incident_id = rule.incident_id
        rule.incident_id = None
        with _LOCK:
            for inc in _INCIDENTS:
                if inc["id"] == incident_id:
                    inc["status"] = "resolved"
                    inc["resolved_t"] = time.time()
                    inc["resolved_now"] = now
        _flight.record("alert", rule=rule.id, state="RESOLVED",
                       incident=incident_id)
        _STATS["alert_transitions"] += 1
        _STATS["alert_incidents_resolved"] += 1
        return "RESOLVED"
    return None


_EVAL_LOCK = threading.Lock()


def evaluate(now=None, force=False, slo=None, input_stall=None):
    """Run every registered rule once against a fresh observation.
    ``now`` (monotonic seconds) defaults to ``time.monotonic()`` —
    tests and drills pass a synthetic clock to drive windows and
    hold/cooldown deterministically. ``slo`` / ``input_stall`` reuse
    values already derived this tick (``update_derived`` shares its
    ``slo_counters()`` view and its input-stall fraction so gauges and
    rules judge the same numbers, each derived once). Returns
    ``{rule_id: transition}`` for the rules that transitioned this
    round, or None when alerting is disabled (pass ``force=True`` to
    evaluate anyway)."""
    if not _ENABLED and not force:
        return None
    if now is None:
        now = time.monotonic()
    with _EVAL_LOCK:
        obs = {"now": now, "seq": _flight.last_seq(),
               "slo": _slo_counters() if slo is None else slo,
               "decode": _decode_counters(),
               "health": _health_counters(),
               "integrity": _integrity_counters()}
        with _LOCK:
            # a clock that moved backwards (a synthetic test clock after
            # a real-clock run, or vice versa) restarts the window
            # history AND re-bases per-rule timestamps into the new
            # clock domain — a rule left FIRING under the old clock
            # would otherwise compare `now - last_breach` across
            # domains and never satisfy its cooldown (stuck open)
            if _HISTORY and _HISTORY[-1]["now"] > now:
                _HISTORY.clear()
                for r in _RULES.values():
                    if r.last_breach is not None and r.last_breach > now:
                        r.last_breach = now
                    if r.pending_since is not None and \
                            r.pending_since > now:
                        r.pending_since = now
            _HISTORY.append(obs)
            history = list(_HISTORY)
            current = list(_RULES.values())
        ctx = _EvalContext(now, obs, history, input_stall=input_stall)
        transitions = {}
        for rule in current:
            try:
                breached, evidence = rule.check(ctx)
            except Exception:
                continue  # one broken rule must never kill the exporter
            t = _advance(rule, breached, evidence, ctx)
            if t:
                transitions[rule.id] = t
        _STATS["alert_evaluations"] += 1
        return transitions


def maybe_evaluate(slo=None, input_stall=None):
    """The exporter-cadence hook (``metrics.update_derived`` calls it,
    passing its already-derived ``slo_counters()`` view and input-stall
    fraction): one global check when disabled, a full :func:`evaluate`
    otherwise, rate-limited to at most one evaluation per
    ``MXNET_TPU_ALERT_EVAL_S`` seconds (0 = every exporter tick). The
    rate limiter keeps its OWN real-monotonic bookkeeping — a drill's
    synthetic evaluation clock must never suppress real exporter
    ticks."""
    global _LAST_TICK
    if not _ENABLED:
        return None
    min_s = _env_float("MXNET_TPU_ALERT_EVAL_S", 0.0)
    real = time.monotonic()
    if min_s > 0 and _LAST_TICK is not None and real - _LAST_TICK < min_s:
        return None
    out = evaluate(slo=slo, input_stall=input_stall)
    _LAST_TICK = real
    return out


def snapshot():
    """The ``observability.dump()`` section: per-rule states plus the
    open-incident count (full incidents ride in ``dump()["incidents"]``)."""
    with _LOCK:
        rules_snap = [r.describe() for r in _RULES.values()]
        n_open = sum(1 for i in _INCIDENTS if i["status"] == "open")
    return {"enabled": _ENABLED, "rules": rules_snap,
            "open_incidents": n_open}


def reset():
    """Restore the default rule set and clear all dynamic state
    (history, incidents, per-rule machines) — tests and drills call
    this between cases."""
    global _LAST_TICK
    with _EVAL_LOCK:
        with _LOCK:
            _RULES.clear()
            _HISTORY.clear()
            _INCIDENTS.clear()
        for rule in _default_rules():
            register_rule(rule)
        _LAST_TICK = None


for _rule in _default_rules():
    register_rule(_rule)
del _rule
