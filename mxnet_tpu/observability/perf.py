"""Performance attribution: the per-executable perf ledger, opt-in
device timing, and MFU / roofline gauges.

PR 10 made the runtime *legible* (span trees, metrics, flight
recorder) but every span still measures host wall-clock around async
dispatch, and nothing attributes cost to the *programs* the runtime
actually runs. This module is the measurement substrate the remaining
ROADMAP items (autotuning, input-stall gates, SLO control loops) stand
on, in three layers:

1. **Static attribution — the perf ledger.** Every compiled executable
   that goes through the sanctioned capture/AOT compile path
   (``capture.aot_compile``: captured trainer steps, ShardedTrainer
   step/grads/apply programs, serving bucket executables in every
   dtype variant) records one ledger entry keyed by its **existing AOT
   fingerprint** (``<label>@<fingerprint16>``): XLA ``cost_analysis()``
   (flops, bytes accessed), ``memory_analysis()`` (argument / output /
   temp / generated-code bytes and the derived peak-HBM estimate) and
   the wall compile time. The ledger is surfaced by
   ``observability.dump()`` / ``tools/obs_dump.py`` and exported as
   per-executable gauges (``mxnet_tpu_executable_peak_hbm_bytes``,
   ``mxnet_tpu_compile_ms``, ...).

2. **Dynamic attribution — device timing.** With
   ``MXNET_TPU_OBS_DEVICE_TIME=1`` (or :func:`set_device_time`), every
   ledgered executable call is wrapped in the dependency-chained
   ``block_until_ready`` timing discipline PERF.md established: the
   span splits into host-dispatch time (the async call returning) and
   device-execute time (until the outputs are ready), recorded as a
   retroactive ``perf.device_execute`` span under the caller's context
   and folded into the ledger entry (``device_ms``, EWMA). OFF by
   default — blocking per call serializes dispatch, so this is a
   diagnosis mode, gated out of the ≤2% obs_bench overhead budget.

3. **Derived gauges — MFU and roofline fraction.** From (1)+(2):
   ``mfu = flops / (device_s · peak_flops)`` and
   ``roofline_fraction = bytes_accessed / (device_s · peak_bw)`` per
   executable, against nominal per-backend peaks (TPU / GPU / CPU
   fallback; override with ``MXNET_TPU_PERF_PEAK_FLOPS`` /
   ``MXNET_TPU_PERF_PEAK_GBPS``). Device time here is the full
   dependency-chained wall (dispatch included) — an upper bound on
   device busy time, so the gauges are conservative.

``tools/perf_gate.py`` turns the ledger + measured step timings into a
continuous regression gate against ``tools/perf_baseline.json``.
Stdlib-only at import (jax loads lazily, and only in the paths that
already hold compiled executables). See docs/observability.md
("Performance attribution") and PERF.md round 6.
"""
from __future__ import annotations

import os
import threading
import time

from . import _STATS
from . import metrics as _metrics

__all__ = ["LEDGER_FIELDS", "note_compile", "note_execution", "timed_call",
           "ledger", "device_timed_entries", "ledger_key",
           "combined_fingerprint", "snapshot", "clear", "update_gauges",
           "device_time_enabled", "set_device_time", "nominal_peaks"]

_LOCK = threading.Lock()
_LEDGER: dict = {}

# THE field registry of one ledger entry. Every entry carries exactly
# these keys (closure-tested), and every field is documented in
# docs/observability.md — graftlint RD005 gates the drift, the same way
# RD001/RD004 pin env knobs and metric names.
LEDGER_FIELDS = (
    "label",                 # compile-site label (trainer_step, serving_bucket8, ...)
    "fingerprint",           # program+signature identity the key derives from
    "backend",               # jax default backend at compile time (cpu/gpu/tpu)
    "compiles",              # times this key compiled this process
    "compile_ms",            # wall time of the latest trace+lower+XLA compile
    "aot_hit",               # latest build deserialized from the AOT disk cache
    "flops",                 # XLA cost_analysis flops (None when unavailable)
    "bytes_accessed",        # XLA cost_analysis bytes accessed (None when unavailable)
    "peak_hbm_bytes",        # argument+output+temp+generated_code-alias estimate
    "argument_bytes",        # memory_analysis argument size
    "output_bytes",          # memory_analysis output size
    "temp_bytes",            # memory_analysis temp size
    "generated_code_bytes",  # memory_analysis generated code size
    "device_calls",          # dependency-chained timed executions (device mode)
    "device_ms",             # EWMA of blocked wall per execution (device mode)
    "dispatch_ms",           # EWMA of the async call returning (device mode)
    "mfu",                   # flops / (device_s * nominal peak flops)
    "roofline_fraction",     # bytes_accessed / (device_s * nominal HBM bandwidth)
    "t",                     # wall-clock of the latest compile
)

_DEVICE_TIME = os.environ.get("MXNET_TPU_OBS_DEVICE_TIME", "").strip() in (
    "1", "true", "on", "yes")

# EWMA smoothing for per-execution device timings: heavy enough that a
# one-off scheduling hiccup doesn't swing the MFU gauge, light enough
# that a real regression shows within ~10 steps.
_EWMA = 0.3

# Nominal per-backend roofs for the MFU/roofline gauges: (flops/s,
# HBM bytes/s). Order-of-magnitude nominals — TPU v4 bf16 MXU + HBM2e,
# A100-class GPU, and a deliberately conservative CPU fallback so the
# gauges are *defined* everywhere tests run. Real deployments override
# per host with MXNET_TPU_PERF_PEAK_FLOPS / MXNET_TPU_PERF_PEAK_GBPS.
_NOMINAL_PEAKS = {
    "tpu": (275.0e12, 1228.0e9),
    "gpu": (312.0e12, 2039.0e9),
    "cpu": (2.0e11, 5.0e10),
}


def device_time_enabled():
    return _DEVICE_TIME


def set_device_time(flag):
    """Toggle dependency-chained device timing at runtime (the
    post-import counterpart of ``MXNET_TPU_OBS_DEVICE_TIME``); returns
    the previous state."""
    global _DEVICE_TIME
    prev = _DEVICE_TIME
    _DEVICE_TIME = bool(flag)
    return prev


def nominal_peaks(backend=None):
    """(peak_flops_per_s, peak_hbm_bytes_per_s) for ``backend``
    (default: jax's default backend, 'cpu' when jax is unavailable),
    with the env overrides applied."""
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    flops, bw = _NOMINAL_PEAKS.get(backend, _NOMINAL_PEAKS["cpu"])
    try:
        flops = float(os.environ.get("MXNET_TPU_PERF_PEAK_FLOPS") or flops)
    except ValueError:
        pass
    try:
        bw = float(os.environ.get("MXNET_TPU_PERF_PEAK_GBPS") or 0) * 1e9 \
            or bw
    except ValueError:
        pass
    return flops, bw


def ledger_key(label, fingerprint):
    """The ledger key: the compile-site label + the first 16 hex chars
    of the site's program+signature identity (see
    :func:`combined_fingerprint` — the same structural identity the
    persistent compile cache is keyed by, so a shape/dtype/code change
    re-keys the entry instead of silently merging two programs)."""
    fp = (fingerprint or "").strip()
    return f"{label}@{fp[:16] if fp else 'none'}"


def combined_fingerprint(fingerprint, sig):
    """Fold a per-call aval/sharding signature into a compile site's
    structural fingerprint — the ledger identity. The AOT disk cache
    keys by (label, fingerprint, sig); a ledger keyed by fingerprint
    alone would merge the distinct programs one CapturedExec compiles
    for different batch shapes (elastic resize, partial tail batch)
    into one entry with last-writer-wins numbers. Both the compile site
    (``capture.aot_compile``) and the execution sites compute this from
    the same inputs, so compile and execution attribution agree."""
    import hashlib

    base = (fingerprint or "").strip()
    if not sig:
        return base
    return hashlib.sha256(f"{base}|{sig}".encode()).hexdigest()[:32]


# ------------------------------------------------------------ cost analysis

def _cost_numbers(compiled):
    """(flops, bytes_accessed) from a compiled executable's XLA cost
    analysis; (None, None) when the backend doesn't expose it. jax
    returns either a per-computation list of dicts or one dict."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    acc = ca.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(acc) if acc is not None else None)


def _memory_numbers(compiled):
    """Memory footprint dict from ``memory_analysis()``; zeros when
    unavailable. ``peak_hbm_bytes`` is the standard estimate
    argument + output + temp + generated_code − alias (donated buffers
    alias their inputs and must not be double-counted), clamped at 0."""
    out = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
           "generated_code_bytes": 0, "peak_hbm_bytes": 0}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return out
    if ma is None:
        return out
    arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
    outp = int(getattr(ma, "output_size_in_bytes", 0) or 0)
    tmp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    gen = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    out.update(argument_bytes=arg, output_bytes=outp, temp_bytes=tmp,
               generated_code_bytes=gen,
               peak_hbm_bytes=max(0, arg + outp + tmp + gen - alias))
    return out


def note_compile(label, fingerprint, compiled, compile_s, aot_hit=False):
    """Record one compile into the ledger (called from
    ``capture.aot_compile`` for every captured/serving executable).
    ``compiled`` may be a lazily-jitted fallback without analysis
    methods — the entry still lands with the wall compile time, so
    `every executable has a ledger entry` holds even where XLA hides
    its cost model. Returns the ledger key."""
    key = ledger_key(label, fingerprint)
    flops, acc = _cost_numbers(compiled)
    mem = _memory_numbers(compiled)
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    with _LOCK:
        entry = _LEDGER.get(key)
        if entry is None:
            entry = dict.fromkeys(LEDGER_FIELDS)
            entry.update(label=label, fingerprint=fingerprint or "",
                         compiles=0, device_calls=0)
            _LEDGER[key] = entry
            _STATS["perf_ledger_entries"] += 1
        entry.update(mem)
        entry.update(backend=backend, compile_ms=compile_s * 1e3,
                     aot_hit=bool(aot_hit), flops=flops,
                     bytes_accessed=acc, t=time.time())
        entry["compiles"] += 1
    return key


def note_execution(label, fingerprint, blocked_s, dispatch_s=0.0):
    """Fold one dependency-chained timed execution into the ledger
    entry and refresh its derived MFU / roofline numbers. ``blocked_s``
    is the full wall from launch until the outputs were ready (the
    PERF.md discipline); ``dispatch_s`` the async call returning."""
    key = ledger_key(label, fingerprint)
    with _LOCK:
        entry = _LEDGER.get(key)
        if entry is None:
            # executions can only follow a compile through aot_compile,
            # but a cleared ledger (tests, gate runs) must not lose the
            # timing — re-seed a minimal entry
            entry = dict.fromkeys(LEDGER_FIELDS)
            entry.update(label=label, fingerprint=fingerprint or "",
                         compiles=0, device_calls=0)
            _LEDGER[key] = entry
            _STATS["perf_ledger_entries"] += 1
        n = entry["device_calls"]
        ms, disp = blocked_s * 1e3, dispatch_s * 1e3
        if n == 0 or entry["device_ms"] is None:
            entry["device_ms"], entry["dispatch_ms"] = ms, disp
        else:
            entry["device_ms"] += _EWMA * (ms - entry["device_ms"])
            entry["dispatch_ms"] += _EWMA * (disp - entry["dispatch_ms"])
        entry["device_calls"] = n + 1
        dev_s = entry["device_ms"] / 1e3
        if dev_s > 0:
            peak_flops, peak_bw = nominal_peaks(entry["backend"])
            if entry["flops"]:
                entry["mfu"] = entry["flops"] / (dev_s * peak_flops)
            if entry["bytes_accessed"]:
                entry["roofline_fraction"] = \
                    entry["bytes_accessed"] / (dev_s * peak_bw)
    _STATS["perf_device_timings"] += 1
    return key


def timed_call(fn, args, label, fingerprint):
    """Execute ``fn(*args)`` under the device-timing discipline when
    enabled; a bare call otherwise (one global check — cheap enough for
    every executable hot path). When timing: measure the async dispatch
    returning, block until every output leaf is ready, record a
    retroactive ``perf.device_execute`` span (host-dispatch vs
    device-execute split in its attrs) under the caller's current trace
    context, and fold the numbers into the ledger."""
    if not _DEVICE_TIME:
        return fn(*args)
    t0 = time.perf_counter_ns()
    out = fn(*args)
    t_disp = time.perf_counter_ns()
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass  # non-array outputs (already-host values) are already ready
    t_ready = time.perf_counter_ns()
    key = note_execution(label, fingerprint, (t_ready - t0) / 1e9,
                         (t_disp - t0) / 1e9)
    from . import trace as _trace

    _trace.record("perf.device_execute", t0, t_ready - t0,
                  executable=key, host_dispatch_ns=t_disp - t0,
                  device_ns=t_ready - t_disp)
    return out


# -------------------------------------------------------------- introspection

def ledger():
    """Snapshot of every entry, keyed by ``<label>@<fingerprint16>``."""
    with _LOCK:
        return {k: dict(v) for k, v in _LEDGER.items()}


def device_timed_entries(min_calls=1):
    """Entries with at least ``min_calls`` dependency-chained timed
    executions and a live ``device_ms`` EWMA — the subscription surface
    for consumers of the dynamic series (the alert engine's
    ``perf_device_regression`` rule watches exactly this view)."""
    with _LOCK:
        return {k: dict(v) for k, v in _LEDGER.items()
                if (v["device_calls"] or 0) >= int(min_calls)
                and v["device_ms"] is not None}


def snapshot():
    """The ``observability.dump()`` section: entries + the roofline
    constants they were judged against + the timing-mode flag."""
    peak_flops, peak_bw = nominal_peaks()
    return {"entries": ledger(),
            "peaks": {"flops_per_s": peak_flops, "hbm_bytes_per_s": peak_bw},
            "device_time": _DEVICE_TIME}


def clear():
    with _LOCK:
        _LEDGER.clear()


# ------------------------------------------------------------ derived gauges

_PEAK_HBM = _metrics.gauge(
    "mxnet_tpu_executable_peak_hbm_bytes",
    "estimated peak HBM of one compiled executable "
    "(argument+output+temp+generated code bytes)", labels=("executable",))
_COMPILE_MS = _metrics.gauge(
    "mxnet_tpu_compile_ms",
    "wall compile time of the executable's latest build",
    labels=("executable",))
_EXEC_FLOPS = _metrics.gauge(
    "mxnet_tpu_executable_flops",
    "XLA cost-analysis flops per execution", labels=("executable",))
_EXEC_BYTES = _metrics.gauge(
    "mxnet_tpu_executable_bytes_accessed",
    "XLA cost-analysis bytes accessed per execution",
    labels=("executable",))
_DEVICE_MS = _metrics.gauge(
    "mxnet_tpu_device_ms",
    "EWMA dependency-chained device time per execution "
    "(MXNET_TPU_OBS_DEVICE_TIME)", labels=("executable",))
_MFU = _metrics.gauge(
    "mxnet_tpu_mfu",
    "model flops utilization vs the backend's nominal peak",
    labels=("executable",))
_ROOFLINE = _metrics.gauge(
    "mxnet_tpu_roofline_fraction",
    "achieved HBM bandwidth fraction vs the backend's nominal peak",
    labels=("executable",))


_PERF_GAUGES = (_PEAK_HBM, _COMPILE_MS, _EXEC_FLOPS, _EXEC_BYTES,
                _DEVICE_MS, _MFU, _ROOFLINE)


def update_gauges():
    """Refresh the per-executable gauges from the ledger — called by
    every exporter via ``metrics.update_derived()``, so the ledger
    exports without any caller wiring (the ``update_slo`` pattern).
    Labelsets whose key left the ledger (re-fingerprinted program,
    ``clear()``) are pruned, so a retrace-churny workload can't accrete
    unbounded label cardinality or export dead executables' frozen
    numbers forever."""
    entries = ledger()
    for g in _PERF_GAUGES:
        for labelset in g.labelsets():
            key = dict(labelset).get("executable")
            if key not in entries:
                g.remove(executable=key)
    for key, e in entries.items():
        _PEAK_HBM.set(e["peak_hbm_bytes"] or 0, executable=key)
        if e["compile_ms"] is not None:
            _COMPILE_MS.set(e["compile_ms"], executable=key)
        if e["flops"] is not None:
            _EXEC_FLOPS.set(e["flops"], executable=key)
        if e["bytes_accessed"] is not None:
            _EXEC_BYTES.set(e["bytes_accessed"], executable=key)
        if e["device_calls"]:
            _DEVICE_MS.set(e["device_ms"], executable=key)
        if e["mfu"] is not None:
            _MFU.set(e["mfu"], executable=key)
        if e["roofline_fraction"] is not None:
            _ROOFLINE.set(e["roofline_fraction"], executable=key)
