"""In-graph numerics telemetry for the captured training step.

The PR-7 whole-program capture made the step a single donated XLA
executable that the observability plane cannot see inside, and the
parity ``Monitor`` forfeits the fused path by forcing op-by-op eager
execution while installed. This module compiles the telemetry *into*
the program instead (the MXNet monitor/executor-callback design and
the TensorFlow production-debuggability argument, PAPERS.md):

- **NumericsTap** — attached to a ``capture.CapturedTrainerStep``, it
  plans one statistics row per tapped tensor (per-parameter gradient /
  weight / optimizer update, per-layer activation) and the captured
  program computes the whole ``(rows, stats)`` float32 matrix
  **on-device** as one extra side output. The sampling cadence and the
  stat selection are **runtime operands** (a gate scalar driving a
  ``lax.cond`` and a column mask), so changing the interval or the
  selected stats at runtime never retraces, and off-cadence steps skip
  the stat reductions entirely.
- **Stat columns** (``NUMERICS_STATS``; graftlint RD007 keeps them
  documented and exercised): ``l2`` (L2 norm), ``maxabs`` (max |x|),
  ``nonfinite`` (NaN/Inf element count), ``underflow`` (fraction of
  nonzero elements that flush to zero in fp16 — the AMP loss-scaling
  regime; bf16 shares fp32's exponent range, so a bf16 underflow at
  fp32 master precision is an fp32 subnormal XLA's FTZ already
  zeroed), ``ratio`` (update-to-param norm ratio; update rows only).
- **Emission** — each sampled step lands in the typed metrics registry
  (``mxnet_tpu_numerics_stat`` by tensor/stat,
  ``mxnet_tpu_numerics_grad_norm``), the flight recorder (kind
  ``numerics``), and a bounded history ring.
- **Divergence conditions** — the tap evaluates three detectors:
  ``nonfinite`` (onset of a non-finite gradient — judged from the
  program's fused all-finite flag EVERY step under the gating
  ``halt``/``skip`` policies, and from the sampled matrix's nonfinite
  column under ``record``), ``grad_explosion`` (global grad norm outside
  median + k*1.4826*MAD of its own clean history), and ``dead_layer``
  (a layer whose gradient stays ~0 / fully fp16-underflowed for N
  consecutive samples while the rest of the net trains). A condition
  turning active writes an automatic **numerics snapshot** (offending
  tensors + optimizer state + the batch, via the checkpoint
  machinery's atomic-write discipline) that
  ``tools/numerics_bisect.py`` replays eagerly to name the first bad
  layer, and surfaces through the ``numerics_*`` alert rules
  (``observability.alerts``) as a correlated Incident.
- **Policy** — ``MXNET_TPU_NONFINITE_POLICY``: ``halt`` raises
  :class:`NumericsDivergenceError` at onset, ``skip`` lets the
  in-program select gate the weight write (the batch never touches the
  weights), ``record`` observes only (bitwise-transparent).

Env knobs (docs/env_vars.md): ``MXNET_TPU_NUMERICS``,
``MXNET_TPU_NUMERICS_INTERVAL``, ``MXNET_TPU_NUMERICS_STATS``,
``MXNET_TPU_NUMERICS_SNAPSHOT_DIR``, ``MXNET_TPU_NUMERICS_SNAPSHOT_KEEP``,
``MXNET_TPU_NONFINITE_POLICY``. Stdlib-only at import (numpy/jax load
lazily inside the capture/emission paths).
"""
from __future__ import annotations

import json
import os
import threading
import time

from collections import deque

from . import _STATS
from . import flight as _flight
from . import metrics as _metrics
from . import trace as _trace

__all__ = ["NumericsTap", "NumericsDivergenceError", "NUMERICS_STATS",
           "NUMERICS_CONDITIONS", "POLICIES", "default_tap", "condition",
           "conditions", "history", "last_snapshot", "snapshots",
           "snapshot_state", "load_snapshot", "reset"]

# THE stat-column registry (graftlint RD007: every token must be
# documented under docs/ and exercised by tests/test_numerics.py or the
# chaos harness). Column order is the on-device matrix layout.
NUMERICS_STATS = ("l2", "maxabs", "nonfinite", "underflow", "ratio")

# Divergence detectors the tap evaluates; each maps 1:1 onto a
# ``numerics_<name>`` alert rule in observability/alerts.py.
NUMERICS_CONDITIONS = ("nonfinite", "grad_explosion", "dead_layer")

POLICIES = ("halt", "skip", "record")

_LOCK = threading.Lock()

# Module-level view the alert rules probe (sys-modules-free: alerts
# lives in the same package). Conditions reflect the most recent tap's
# detector state; history is the sampled time series.
_CONDITIONS: dict = {}
_HISTORY: deque = deque(maxlen=512)
_SNAPSHOTS: list = []
_LAST_SAMPLE = None

_GAUGE = _metrics.gauge(
    "mxnet_tpu_numerics_stat",
    "latest in-graph numerics statistic, by tapped tensor and stat",
    labels=("tensor", "stat"))
_GAUGE_GRAD_NORM = _metrics.gauge(
    "mxnet_tpu_numerics_grad_norm",
    "global gradient L2 norm from the captured step's in-graph tap")


class NumericsDivergenceError(ArithmeticError):
    """Training numerics diverged (non-finite gradients) under the
    ``halt`` policy of the in-graph numerics tap."""


def _env_policy():
    p = os.environ.get("MXNET_TPU_NONFINITE_POLICY", "halt").strip() \
        or "halt"
    if p not in POLICIES:
        raise ValueError(
            f"MXNET_TPU_NONFINITE_POLICY must be one of {POLICIES}, "
            f"got {p!r}")
    return p


def default_tap():
    """The tap ``capture.CapturedTrainerStep`` arms when the operator
    sets ``MXNET_TPU_NUMERICS`` (truthy); None otherwise, which keeps
    the captured program bit-identical to the pre-telemetry build."""
    if os.environ.get("MXNET_TPU_NUMERICS", "").strip().lower() in (
            "", "0", "false", "off", "no"):
        return None
    return NumericsTap()


def condition(name):
    """The detector state the ``numerics_<name>`` alert rule probes:
    ``{"active", "since_step", "evidence", "snapshot"}`` — or None when
    no tap has ever judged this condition (rule stays inert)."""
    with _LOCK:
        c = _CONDITIONS.get(name)
        return dict(c) if c is not None else None


def conditions():
    with _LOCK:
        return {k: dict(v) for k, v in _CONDITIONS.items()}


def history():
    """Sampled numerics observations, oldest first: ``{"t", "step",
    "grad_norm", "grads": {tensor: l2}, "nonfinite_rows": [...]}``."""
    with _LOCK:
        return [dict(h) for h in _HISTORY]


def last_snapshot():
    with _LOCK:
        return _SNAPSHOTS[-1] if _SNAPSHOTS else None


def snapshots():
    with _LOCK:
        return list(_SNAPSHOTS)


def snapshot_state():
    """The ``observability.dump()["numerics"]`` section."""
    with _LOCK:
        last = dict(_LAST_SAMPLE) if _LAST_SAMPLE else None
    return {"stats": list(NUMERICS_STATS),
            "conditions": conditions(),
            "last_sample": last,
            "history_len": len(_HISTORY),
            "snapshots": snapshots()}


def reset():
    """Clear conditions, history and snapshot bookkeeping (tests and
    drills call this between cases; on-disk snapshots are not
    deleted)."""
    global _LAST_SAMPLE
    with _LOCK:
        _CONDITIONS.clear()
        _HISTORY.clear()
        del _SNAPSHOTS[:]
        _LAST_SAMPLE = None


def _set_condition(name, active, evidence=None, step=None, snapshot=None):
    """Transition one detector; records a flight event on every flip so
    the incident's evidence window shows exactly when numerics went bad
    (and came back)."""
    with _LOCK:
        cur = _CONDITIONS.get(name)
        was = bool(cur and cur["active"])
        if cur is None:
            cur = _CONDITIONS[name] = {
                "active": False, "since_step": None, "evidence": None,
                "snapshot": None}
        cur["active"] = bool(active)
        if active:
            if not was:
                cur["since_step"] = step
            cur["evidence"] = evidence or {}
            if snapshot is not None:
                cur["snapshot"] = snapshot
    if bool(active) != was:
        _flight.record("numerics", op="condition", condition=name,
                       active=bool(active), step=step)
    return bool(active) != was


# ------------------------------------------------------------------ the tap

class NumericsTap:
    """Per-layer/per-param numerics telemetry compiled into a captured
    training step.

    Parameters
    ----------
    interval : int — sample every Nth step (``MXNET_TPU_NUMERICS_INTERVAL``,
        default 10; ``0`` disables sampling — the side output stays in
        the program, zero-filled, so flipping sampling back on never
        retraces). Change at runtime with :meth:`set_interval`.
    stats : iterable of ``NUMERICS_STATS`` names — the selected columns
        (``MXNET_TPU_NUMERICS_STATS`` comma list, default all).
        Unselected columns are zeroed by the in-program mask operand;
        change at runtime with :meth:`set_stats` — never a retrace.
    policy : ``halt`` | ``skip`` | ``record`` — what a non-finite
        gradient does (``MXNET_TPU_NONFINITE_POLICY``, default
        ``halt``). ``halt``/``skip`` gate the weight write in-program
        (so the bad batch never lands) and then raise / skip on the
        host; ``record`` is observation-only and keeps the program
        bitwise-transparent even on bad batches. Baked into the program
        (changing it recaptures).
    snapshot_dir : where divergence snapshots publish
        (``MXNET_TPU_NUMERICS_SNAPSHOT_DIR``; default
        ``<tempdir>/mxnet_tpu_numerics``).
    """

    def __init__(self, interval=None, stats=None, policy=None,
                 snapshot_dir=None, history_n=128, mad_k=None,
                 explosion_min_n=8, dead_eps=1e-12, dead_n=8):
        if interval is None:
            try:
                interval = int(os.environ.get(
                    "MXNET_TPU_NUMERICS_INTERVAL", "10"))
            except ValueError:
                interval = 10
        if stats is None:
            raw = os.environ.get("MXNET_TPU_NUMERICS_STATS", "").strip()
            stats = tuple(s.strip() for s in raw.split(",") if s.strip()) \
                if raw else NUMERICS_STATS
        unknown = sorted(set(stats) - set(NUMERICS_STATS))
        if unknown:
            raise ValueError(
                f"unknown numerics stats {unknown}; pick from "
                f"{NUMERICS_STATS}")
        self.policy = _env_policy() if policy is None else policy
        if self.policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {self.policy!r}")
        self.snapshot_dir = snapshot_dir
        self._interval = max(0, int(interval))
        self._selected = tuple(s for s in NUMERICS_STATS if s in set(stats))
        self._step = 0
        self._force_next = False
        self._sel_cache = None
        self._listeners = []
        # capture-build state
        self.rows = ()          # ((name, size), ...) fixed at build
        self._net = None
        self._trainer = None
        self._last_batch = None
        # detector state
        self._mad_k = float(os.environ.get("MXNET_TPU_NUMERICS_MAD_K",
                                           "8") if mad_k is None else mad_k)
        self._explosion_min_n = int(explosion_min_n)
        self._norm_hist = deque(maxlen=int(history_n))
        self._dead_eps = float(dead_eps)
        self._dead_n = int(dead_n)
        self._dead_counts = {}
        self._clean_steps = 0
        self._nonfinite_steps = 0

    # ------------------------------------------------------ runtime knobs
    @property
    def interval(self):
        return self._interval

    def set_interval(self, n):
        """Change the sampling cadence at runtime — a pure operand
        change, never a retrace (tested by the compile-count probe)."""
        self._interval = max(0, int(n))
        return self

    @property
    def selected(self):
        return self._selected

    def set_stats(self, stats):
        """Change the selected stat columns at runtime — the in-program
        column mask is an operand, never a retrace."""
        unknown = sorted(set(stats) - set(NUMERICS_STATS))
        if unknown:
            raise ValueError(
                f"unknown numerics stats {unknown}; pick from "
                f"{NUMERICS_STATS}")
        self._selected = tuple(s for s in NUMERICS_STATS
                               if s in set(stats))
        self._sel_cache = None
        return self

    def request_sample(self):
        """Force the NEXT step to sample regardless of cadence (the
        compiled-tap ``Monitor`` calls this from ``tic()``)."""
        self._force_next = True
        return self

    def add_listener(self, fn):
        """``fn(step, stats_by_tensor)`` called on every sampled step
        (``stats_by_tensor``: ``{name: {"size": n, <stat>: value}}``)."""
        self._listeners.append(fn)
        return fn

    def sel_values(self):
        """The column-mask operand for the selected stats (cached: the
        steady-state step builds no per-step numpy garbage)."""
        cached = self._sel_cache
        if cached is None:
            import numpy as np

            cached = self._sel_cache = np.asarray(
                [1.0 if s in self._selected else 0.0
                 for s in NUMERICS_STATS], np.float32)
        return cached

    def tick(self):
        """Advance the tap's step counter; True when this step samples
        (cadence hit or a forced sample)."""
        step = self._step
        self._step += 1
        sampled = self._force_next or (
            self._interval > 0 and step % self._interval == 0)
        self._force_next = False
        return sampled

    @property
    def gates_updates(self):
        """Whether the captured program's weight-write select also gates
        on the fused finite flag for this tap (``halt``/``skip``): a
        non-finite batch never touches the weights. ``record`` keeps
        the program bitwise-transparent."""
        return self.policy in ("halt", "skip")

    # -------------------------------------------------------- capture-side
    def bind(self, net, trainer):
        self._net = net
        self._trainer = trainer
        return self

    def plan_signature(self):
        """The tap's contribution to the capture fingerprint: row plan +
        column schema + gating semantics (a changed plan or policy is a
        different program; interval/selection are operands and do NOT
        appear here)."""
        return {"rows": tuple(n for n, _ in self.rows),
                "stats": NUMERICS_STATS,
                "gates": self.gates_updates}

    def install_hooks(self, net):
        """Register transient forward hooks on every leaf block; returns
        ``(handles, acts)`` where ``acts`` fills with
        ``(name, raw_jax_value)`` in forward call order. The caller
        removes the handles right after the forward (the hooks must not
        leak into later eager use of the net)."""
        handles = []
        acts = []
        counts = {}

        def make_hook(name):
            def hook(block, inputs, out):
                outs = out if isinstance(out, (list, tuple)) else (out,)
                k = counts.get(name, 0)
                counts[name] = k + 1
                for i, o in enumerate(outs):
                    data = getattr(o, "data_", None)
                    if data is None:
                        continue
                    tag = name if k == 0 else f"{name}#{k}"
                    if len(outs) > 1:
                        tag = f"{tag}:{i}"
                    acts.append((tag, data))
            return hook

        def register(blk):
            if not blk._children:
                handles.append(
                    blk.register_forward_hook(make_hook(blk.name)))
                return
            for child in blk._children.values():
                register(child)

        register(net)
        return handles, acts

    @staticmethod
    def remove_hooks(handles):
        for h in handles:
            h.detach()

    def tapped_params(self, trainer):
        return [p for p in trainer._params if p.grad_req != "null"]

    def graph_stats(self, grads, params_pre, params_post, acts, sel_t):
        """Build the on-device ``(rows, len(NUMERICS_STATS))`` float32
        stats matrix from the traced step's tensors — the side output
        of the SAMPLED-step program variant (off-cadence steps run the
        base variant, which contains none of this). ``sel_t`` is the
        column-mask operand (stat selection changes re-bind the mask,
        never retrace). Also records ``self.rows`` (name, size) — the
        fixed row plan the emission path decodes by."""
        import jax.numpy as jnp

        # (row-name, kind, payload) — payloads are the RAW traced
        # tensors; derived tensors (updates) materialize inside compute
        named = [(f"grad:{name}", "plain", g) for name, g in grads]
        named += [(f"param:{name}", "plain", p) for name, p in params_pre]
        named += [(f"update:{name}", "update", (post, pre))
                  for (name, pre), (_, post) in zip(params_pre,
                                                    params_post)]
        named += [(f"act:{name}", "plain", a) for name, a in acts]

        def size_of(kind, x):
            return int(getattr(x[0] if kind == "update" else x,
                               "size", 1))

        self.rows = tuple((name, size_of(kind, x))
                          for name, kind, x in named)
        n_rows = len(named)
        n_cols = len(NUMERICS_STATS)
        if n_rows == 0:
            return jnp.zeros((0, n_cols), jnp.float32)

        def one_row(x, den):
            v = jnp.asarray(x).astype(jnp.float32).ravel()
            l2 = jnp.sqrt(jnp.sum(v * v))
            maxabs = jnp.max(jnp.abs(v))
            nonfinite = jnp.sum(
                (~jnp.isfinite(v)).astype(jnp.float32))
            # fraction of NONZERO elements flushing to zero in fp16 —
            # the low-precision regime the AMP LossScaler guards (bf16
            # shares fp32's exponent range, so a "bf16 underflow" at
            # fp32 master precision is already an fp32 subnormal that
            # XLA's FTZ zeroes before any comparison could see it).
            # Nonzero denominator: a ReLU gradient that is 40% exact
            # zeros and otherwise fully sub-fp16 must read 1.0, or the
            # dead-layer detector's >=0.99 bar could never fire
            nonzero = jnp.sum((v != 0.0).astype(jnp.float32))
            under = jnp.sum(jnp.logical_and(
                v != 0.0,
                v.astype(jnp.float16) == 0.0).astype(jnp.float32)) \
                / jnp.maximum(nonzero, 1.0)
            if den is None:
                ratio = jnp.float32(0.0)
            else:
                d = jnp.asarray(den).astype(jnp.float32).ravel()
                ratio = l2 / (jnp.sqrt(jnp.sum(d * d)) + 1e-12)
            return jnp.stack([l2, maxabs, nonfinite, under, ratio])

        rows = []
        for _name, kind, x in named:
            if kind == "update":
                post, pre = x
                rows.append(one_row(
                    jnp.asarray(post) - jnp.asarray(pre), pre))
            else:
                rows.append(one_row(x, None))
        return jnp.stack(rows) * jnp.asarray(sel_t, jnp.float32)[None, :]

    # --------------------------------------------------------- host-side
    def on_step(self, step, finite_ok, stats_np, batch=None):
        """Per-step host hook from the captured call: ``finite_ok`` is
        the program's fused all-finite flag (every step), ``stats_np``
        the pulled stats matrix on sampled steps (None otherwise).
        Updates metrics/flight/history, evaluates the divergence
        conditions, and applies the non-finite policy."""
        if batch is not None:
            self._last_batch = batch
        sample = None
        if stats_np is not None:
            with _trace.span("numerics.sample", step=step):
                sample = self._emit(step, stats_np)
        if finite_ok is None and sample is not None \
                and "nonfinite" in self._selected:
            # record-policy programs carry no per-step finite flag: the
            # sampled matrix's nonfinite column is the finite signal
            finite_ok = not sample["nonfinite_rows"]
        if finite_ok is not None:
            self._judge_nonfinite(step, finite_ok, sample)
        if sample is not None and (finite_ok is None or finite_ok):
            self._judge_explosion(step, sample)
            self._judge_dead_layers(step, sample)

    # emission ----------------------------------------------------------
    def _emit(self, step, stats_np):
        global _LAST_SAMPLE
        import numpy as np

        _STATS["numerics_samples"] += 1
        mat = np.asarray(stats_np, np.float64)
        by_tensor = {}
        grads = {}
        under = {}
        nonfinite_rows = []
        grad_sq = 0.0
        sel = set(self._selected)
        for i, (name, size) in enumerate(self.rows):
            if i >= mat.shape[0]:
                break
            rec = {"size": size}
            for j, stat in enumerate(NUMERICS_STATS):
                if stat not in sel:
                    continue
                val = float(mat[i, j])
                rec[stat] = val
                self._gauge_set(name, stat, val)
            by_tensor[name] = rec
            l2 = rec.get("l2")
            if name.startswith("grad:"):
                if l2 is not None:
                    grads[name[5:]] = l2
                    if np.isfinite(l2):
                        grad_sq += l2 * l2
                if "underflow" in rec:
                    under[name[5:]] = rec["underflow"]
            nf = rec.get("nonfinite")
            if nf:
                nonfinite_rows.append(name)
        grad_norm = float(np.sqrt(grad_sq)) if "l2" in sel else None
        if grad_norm is not None:
            _GAUGE_GRAD_NORM.set(grad_norm)
        sample = {"t": time.time(), "step": step, "grad_norm": grad_norm,
                  "grads": grads, "underflow": under,
                  "nonfinite_rows": nonfinite_rows,
                  # full per-tensor stats: what a numerics snapshot
                  # records as the CAPTURED run's reference values for
                  # tools/numerics_bisect.py's eager-replay comparison
                  "tensors": by_tensor}
        with _LOCK:
            _HISTORY.append(sample)
            _LAST_SAMPLE = sample
        _flight.record("numerics", op="sample", step=step,
                       grad_norm=grad_norm,
                       nonfinite_rows=len(nonfinite_rows))
        for fn in self._listeners:
            try:
                fn(step, by_tensor)
            except Exception:
                pass  # a broken listener must never fail the step
        return sample

    def _gauge_set(self, tensor, stat, value):
        _GAUGE.set(value, tensor=tensor, stat=stat)

    # detectors ---------------------------------------------------------
    def _judge_nonfinite(self, step, finite_ok, sample):
        if finite_ok:
            self._clean_steps += 1
            # a few consecutive clean steps = the divergence is over
            if self._nonfinite_steps and self._clean_steps >= 4:
                self._nonfinite_steps = 0
                _set_condition("nonfinite", False, step=step)
            return
        self._clean_steps = 0
        self._nonfinite_steps += 1
        _STATS["numerics_nonfinite_steps"] += 1
        evidence = {"nonfinite_steps": self._nonfinite_steps,
                    "policy": self.policy}
        if sample is not None and sample["nonfinite_rows"]:
            evidence["nonfinite_rows"] = sample["nonfinite_rows"]
            evidence["first_nonfinite"] = sample["nonfinite_rows"][0]
            # forward-order activation onset names the offending LAYER
            # (a NaN source poisons every gradient via backward, but
            # only the layers downstream of it in the forward)
            for name in sample["nonfinite_rows"]:
                if name.startswith("act:"):
                    evidence["first_nonfinite_act"] = name
                    break
        flipped = _set_condition("nonfinite", True, evidence=evidence,
                                 step=step)
        if flipped:
            path = self.write_snapshot("nonfinite", step=step,
                                       stats=sample)
            if path is not None:
                _set_condition("nonfinite", True, evidence=evidence,
                               step=step, snapshot=path)
        if self.policy == "halt":
            _STATS["numerics_halts"] += 1
            raise NumericsDivergenceError(
                f"non-finite gradient at captured step {step} "
                f"(policy=halt; snapshot: {last_snapshot()})")

    def _judge_explosion(self, step, sample):
        norm = sample.get("grad_norm")
        if norm is None or not _finite(norm):
            return
        hist = self._norm_hist
        if len(hist) >= self._explosion_min_n:
            med = _median(hist)
            mad = _median([abs(v - med) for v in hist])
            sigma = 1.4826 * mad
            # spread floor (5% of median) + a hard 4x floor: only a
            # multiple-of-itself explosion can page, never CI jitter
            limit = max(med + self._mad_k * max(sigma, 0.05 * med),
                        4.0 * med)
            if med > 0 and norm > limit:
                evidence = {"grad_norm": norm, "limit": limit,
                            "median": med, "mad": mad, "k": self._mad_k,
                            "n": len(hist), "step": step}
                flipped = _set_condition("grad_explosion", True,
                                         evidence=evidence, step=step)
                if flipped:
                    path = self.write_snapshot("grad_explosion",
                                               step=step, stats=sample)
                    if path is not None:
                        _set_condition("grad_explosion", True,
                                       evidence=evidence, step=step,
                                       snapshot=path)
                return  # outliers stay out of their own baseline
        hist.append(norm)
        _set_condition("grad_explosion", False, step=step)

    def _judge_dead_layers(self, step, sample):
        grads = sample.get("grads") or {}
        under = sample.get("underflow") or {}
        norm = sample.get("grad_norm")
        if not grads:
            return
        dead = []
        for name, l2 in grads.items():
            is_dead = l2 <= self._dead_eps \
                or under.get(name, 0.0) >= 0.99
            n = self._dead_counts.get(name, 0) + 1 if is_dead else 0
            self._dead_counts[name] = n
            if n >= self._dead_n:
                dead.append(name)
        # a globally-dead net (norm ~0) is "training finished/broken",
        # not one dead layer among live ones
        if dead and norm is not None and norm > self._dead_eps \
                and len(dead) < len(grads):
            evidence = {"dead_layers": sorted(dead),
                        "samples": self._dead_n, "step": step}
            flipped = _set_condition("dead_layer", True,
                                     evidence=evidence, step=step)
            if flipped:
                path = self.write_snapshot("dead_layer", step=step,
                                           stats=sample)
                if path is not None:
                    _set_condition("dead_layer", True, evidence=evidence,
                                   step=step, snapshot=path)
        else:
            _set_condition("dead_layer", False, step=step)

    # snapshots ---------------------------------------------------------
    def _snapshot_root(self):
        d = self.snapshot_dir \
            or os.environ.get("MXNET_TPU_NUMERICS_SNAPSHOT_DIR", "").strip()
        if not d:
            import tempfile

            d = os.path.join(tempfile.gettempdir(), "mxnet_tpu_numerics")
        return d

    def write_snapshot(self, reason, step=None, stats=None):
        """Publish one numerics snapshot — the forensic bundle
        ``tools/numerics_bisect.py`` replays: the batch, every
        parameter, the optimizer state (``Trainer.get_states_bytes``)
        and the tap's row stats — through the checkpoint machinery's
        atomic-write discipline (fsynced files in a temp dir, one final
        rename). Returns the published path, or None when the tap has
        no bound net/trainer. Never raises: a full disk must not take
        the training step down with it."""
        if self._net is None or self._trainer is None:
            return None
        try:
            return self._write_snapshot_impl(reason, step, stats)
        except Exception:
            return None

    def _write_snapshot_impl(self, reason, step, stats):
        import io as _io

        import numpy as np

        from ..resilience.checkpoint import atomic_write_bytes

        root = self._snapshot_root()
        os.makedirs(root, exist_ok=True)
        tag = f"numerics-{step if step is not None else self._step:08d}" \
              f"-{int(time.time() * 1000) % 100000:05d}"
        final = os.path.join(root, tag)
        tmp = os.path.join(root, f".tmp-{tag}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)

        params = {name: nd.asnumpy()
                  for name, nd in
                  self._net._collect_params_with_prefix().items()}
        buf = _io.BytesIO()
        np.savez(buf, **params)
        atomic_write_bytes(os.path.join(tmp, "params.npz"),
                           buf.getvalue())
        batch_files = None
        if self._last_batch is not None:
            x_nd, y_nd = self._last_batch
            buf = _io.BytesIO()
            np.savez(buf, x=np.asarray(x_nd.asnumpy()),
                     y=np.asarray(y_nd.asnumpy()))
            atomic_write_bytes(os.path.join(tmp, "batch.npz"),
                               buf.getvalue())
            batch_files = "batch.npz"
        atomic_write_bytes(os.path.join(tmp, "trainer.state"),
                           self._trainer.get_states_bytes())
        manifest = {
            "schema": 1,
            "reason": reason,
            "step": step,
            "t": time.time(),
            "policy": self.policy,
            "stats_schema": list(NUMERICS_STATS),
            "selected": list(self._selected),
            "rows": [[n, s] for n, s in self.rows],
            "sample": stats,
            "params": "params.npz",
            "batch": batch_files,
            "trainer_state": "trainer.state",
            "param_names": sorted(params),
        }
        atomic_write_bytes(
            os.path.join(tmp, "manifest.json"),
            json.dumps(manifest, sort_keys=True, default=str).encode())
        os.replace(tmp, final)
        _STATS["numerics_snapshots"] += 1
        _flight.record("numerics", op="snapshot", reason=reason,
                       step=step, path=final)
        with _LOCK:
            _SNAPSHOTS.append(final)
            del _SNAPSHOTS[:-16]
        self._prune(root)
        return final

    @staticmethod
    def _prune(root):
        try:
            keep = int(os.environ.get(
                "MXNET_TPU_NUMERICS_SNAPSHOT_KEEP", "4"))
        except ValueError:
            keep = 4
        if keep <= 0:
            return
        try:
            entries = []
            for name in os.listdir(root):
                if not name.startswith("numerics-"):
                    continue
                path = os.path.join(root, name)
                try:
                    entries.append((os.path.getmtime(path), path))
                except OSError:
                    continue
        except OSError:
            return
        import shutil

        # mtime order, NOT name order: the tag leads with the step
        # number, so after a restart a new run's step-5 snapshot would
        # sort before an old run's step-400 ones and be pruned first —
        # deleting exactly the forensic bundle the fresh incident's
        # evidence points at
        entries.sort()
        for _, path in entries[:-keep]:
            shutil.rmtree(path, ignore_errors=True)


def load_snapshot(path):
    """Read one published numerics snapshot back:
    ``{"manifest", "params": {name: np}, "batch": (x, y) | None,
    "trainer_state": bytes}`` (the bisect tool's input)."""
    import numpy as np

    with open(os.path.join(path, "manifest.json"), encoding="utf-8") as f:
        manifest = json.load(f)
    with np.load(os.path.join(path, manifest["params"])) as z:
        params = {k: z[k].copy() for k in z.files}
    batch = None
    if manifest.get("batch"):
        with np.load(os.path.join(path, manifest["batch"])) as z:
            batch = (z["x"].copy(), z["y"].copy())
    state = None
    st = manifest.get("trainer_state")
    if st and os.path.isfile(os.path.join(path, st)):
        with open(os.path.join(path, st), "rb") as f:
            state = f.read()
    return {"manifest": manifest, "params": params, "batch": batch,
            "trainer_state": state}


def _median(values):
    vals = sorted(values)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0


def _finite(v):
    return v == v and v not in (float("inf"), float("-inf"))
