"""Always-on flight recorder: one bounded chronological event log.

Before this module existed the runtime's forensic trail was scattered:
the eager dispatch ring in the profiler, fault fire counts in
``resilience.faults``, retrace reasons in ``capture.retrace_log()``,
fleet transitions in per-replica deques — each with its own format and
none of them interleaved in time. The flight recorder unifies them: any
subsystem calls :func:`record` with a ``kind`` and flat fields, and the
event lands in one ring ordered by a global sequence number, cheap
enough to leave on in production (a dict build + a deque append under a
lock, ~1 us).

Event kinds recorded by the runtime (docs/observability.md has the
schema):

``span``       root-span ends (step / request timelines; trace.py)
``fault``      an armed fault fired (resilience.faults)
``stall``      a watchdog deadline expired (resilience.watchdog)
``peer``       a rank was declared dead / recovered (watchdog)
``ckpt``       a checkpoint published / restored (resilience.checkpoint)
``retrace``    a captured program recompiled, with the reason (capture)
``fleet``      a replica state transition (serving.fleet)
``monitor``    a Monitor tensor-stat emission (mxnet_tpu.monitor)
``perf``       a perf-gate regression (tools/perf_gate.py)
``alert``      an alert rule transitioned FIRING/RESOLVED (alerts)
``numerics``   an in-graph numerics sample / divergence-condition flip
               / snapshot publish (observability.numerics)

The ring is sized by ``MXNET_TPU_OBS_FLIGHT_RING`` (default 1024 events,
``0`` disables; resize at runtime with :func:`set_ring`). Watchdog crash
reports embed :func:`snapshot`'s tail, and ``observability.dump()`` /
``tools/obs_dump.py`` expose it on demand. Stdlib-only at import.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

from . import _STATS

__all__ = ["record", "events", "snapshot", "clear", "set_ring",
           "ring_size", "last_seq", "set_host", "host"]

from collections import deque

_LOCK = threading.Lock()
try:
    _RING_SIZE = int(os.environ.get("MXNET_TPU_OBS_FLIGHT_RING", "1024"))
except ValueError:
    _RING_SIZE = 1024
_RING = deque(maxlen=_RING_SIZE) if _RING_SIZE > 0 else None
_SEQ = itertools.count(1)
_LAST_SEQ = 0


def set_ring(size):
    """Resize (or with ``size <= 0`` disable) the flight ring at
    runtime; returns the previous size. Existing events are kept up to
    the new capacity (newest win)."""
    global _RING
    size = int(size)
    with _LOCK:
        prev = _RING.maxlen if _RING is not None else 0
        if size > 0:
            _RING = deque(_RING or (), maxlen=size)
        else:
            _RING = None
    return prev


def ring_size():
    with _LOCK:
        return _RING.maxlen if _RING is not None else 0


_HOST = None  # pod host rank stamped onto every event (None = untagged)


def set_host(host):
    """Tag every subsequent event with this process's pod host rank
    (``watchdog.configure_pod`` calls this), so one pod-wide merge of
    per-host rings still attributes each event to its failure domain.
    ``None`` removes the tag; returns the previous value."""
    global _HOST
    prev = _HOST
    _HOST = None if host is None else int(host)
    return prev


def host():
    """The pod host rank events are currently tagged with, or None."""
    return _HOST


def record(kind, **fields):
    """Append one event. ``fields`` must be flat JSON-serializable
    values (the crash-report writer stringifies anything else). Events
    carry the pod host rank when :func:`set_host` has been called (an
    explicit ``host=`` field wins). Returns the event's sequence number,
    or 0 when the recorder is disabled."""
    global _LAST_SEQ
    if _RING is None:
        return 0
    event = {"seq": 0, "t": time.time(), "ns": time.perf_counter_ns(),
             "kind": str(kind)}
    for k, v in fields.items():
        event.setdefault(k, v)  # kind/seq/t/ns are the recorder's own
    if _HOST is not None:
        event.setdefault("host", _HOST)  # explicit host= field wins
    with _LOCK:
        # seq is drawn under the SAME lock hold as the append, so ring
        # order always equals seq order and last_seq() is a sound
        # "events after this" bookmark (the chaos-gate contract)
        seq = event["seq"] = next(_SEQ)
        if _RING is not None:
            _RING.append(event)
        _LAST_SEQ = seq
    _STATS["obs_flight_events"] += 1
    return seq


def events(kind=None, since_seq=0):
    """Events currently in the ring, oldest first; optionally filtered
    to one ``kind`` and/or to events after ``since_seq`` (use
    :func:`last_seq` to bookmark)."""
    with _LOCK:
        out = list(_RING) if _RING is not None else []
    if kind is not None:
        out = [e for e in out if e["kind"] == kind]
    if since_seq:
        out = [e for e in out if e["seq"] > since_seq]
    return out


def snapshot(limit=None):
    """The ring's tail (newest ``limit`` events, oldest first) — the
    form watchdog crash reports embed."""
    with _LOCK:
        out = list(_RING) if _RING is not None else []
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def last_seq():
    """The most recently issued sequence number (a bookmark for
    ``events(since_seq=...)``); monotonic even across ring overflow
    and :func:`clear`."""
    with _LOCK:
        return _LAST_SEQ


def clear():
    with _LOCK:
        if _RING is not None:
            _RING.clear()
