"""Structured span tracing: low-overhead per-step / per-request timelines.

One *span* is one timed phase of work (``perf_counter_ns`` start/end)
with a name, flat attributes, and a position in a tree: every span
carries the ``trace_id`` of its root and the ``span_id`` of its parent,
held in a thread-local context that nests naturally with the ``with``
statement. The instrumented runtime (docs/observability.md, "span
taxonomy") gives every training step and every serving request a
complete timeline:

- ``train.step`` > ``step.data_wait`` / ``step.h2d`` /
  ``step.allreduce`` / ``step.sentinel`` / ``step.update`` /
  ``step.execute`` / ``step.ckpt_stall``
- ``serve.request`` > ``serve.attempt`` > ``serve.batch`` >
  ``serve.batch_form`` / ``serve.execute`` / ``serve.sentinel``

Cross-thread propagation is explicit: a producer captures
:func:`current` and a consumer re-enters it with :func:`context` (the
serving batcher does this per request). Across the fleet's
process-replica pipe the *context ships with the request* and the
child's span records ship back with the reply (:func:`collect` on the
child side, :func:`ingest` on the parent side), so one request is one
connected tree even when its batch executed in another process.

Cost model: tracing is OFF by default (``MXNET_TPU_OBS_TRACE=1`` or
:func:`set_enabled`); a disabled ``trace.span(...)`` returns a shared
no-op context manager — one function call, one global check — and the
``tools/obs_bench.py`` gate pins the enabled cost to <= 2% of a step.
Ended spans land in a bounded ring (``MXNET_TPU_OBS_SPAN_RING``,
default 4096); root-span ends also feed the flight recorder and the
``mxnet_tpu_span_ms`` histogram. Stdlib-only at import.
"""
from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time

from collections import deque

from . import _STATS, flight as _flight
from . import metrics as _metrics

__all__ = ["span", "start_span", "record", "current", "context",
           "collect", "ingest", "spans", "roots", "clear", "enabled",
           "set_enabled", "new_trace_id", "Span"]

try:
    _RING_SIZE = int(os.environ.get("MXNET_TPU_OBS_SPAN_RING", "4096"))
except ValueError:
    _RING_SIZE = 4096
_RING_LOCK = threading.Lock()
_RING = deque(maxlen=max(1, _RING_SIZE))

_ENABLED = os.environ.get("MXNET_TPU_OBS_TRACE", "").strip() in (
    "1", "true", "on", "yes")

_TLS = threading.local()
_IDS = itertools.count(1)
# pid + a random salt disambiguate ids across processes (spawned fleet
# replicas ship their span records back over the pipe) and pid reuse.
# Both are cached at import: os.getpid() is a syscall (microseconds
# under a traced sandbox) and ids are built on the span hot path.
_SALT = os.urandom(2).hex()
_PID_HEX = f"{os.getpid():x}"


def enabled():
    return _ENABLED


def set_enabled(flag):
    """Turn span tracing on/off at runtime (the post-import counterpart
    of ``MXNET_TPU_OBS_TRACE``); returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


def new_trace_id():
    return f"{_SALT}{_PID_HEX}-{next(_IDS):x}"


def _new_span_id():
    return f"{_PID_HEX}.{next(_IDS):x}"


def current():
    """The active context as ``(trace_id, span_id)``, or None. This is
    the token a producer hands a consumer thread (or ships over a pipe)
    so work done elsewhere parents correctly."""
    return getattr(_TLS, "ctx", None)


class Span:
    """One open span. Usually managed by ``with trace.span(...)``; the
    router uses :func:`start_span` + :meth:`end` explicitly because a
    request span outlives the submitting thread."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "t0_ns", "_prev_ctx", "_entered", "_done")

    def __init__(self, name, parent_ctx, attrs):
        if parent_ctx is None:
            self.trace_id = new_trace_id()
            self.parent_id = None
        else:
            self.trace_id, self.parent_id = parent_ctx
        self.span_id = _new_span_id()
        self.name = name
        self.attrs = attrs
        self.t0_ns = time.perf_counter_ns()
        self._prev_ctx = None
        self._entered = False
        self._done = False

    @property
    def ctx(self):
        return (self.trace_id, self.span_id)

    def set(self, **attrs):
        """Attach attributes after the fact (outcome, row counts)."""
        self.attrs.update(attrs)
        return self

    def end(self, **attrs):
        """Close the span and place its record in the ring. Idempotent
        (a router request span may race its own expiry action)."""
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        dur = time.perf_counter_ns() - self.t0_ns
        rec = {"trace": self.trace_id, "span": self.span_id,
               "parent": self.parent_id, "name": self.name,
               "t0_ns": self.t0_ns, "dur_ns": dur,
               "thread": threading.current_thread().name,
               "attrs": self.attrs}
        _store(rec)
        if self.parent_id is None:
            # scalar attrs ride into the flight event, minus the keys
            # the event itself owns (an attr literally named "name"/
            # "trace"/"dur_ns" must not TypeError the span end)
            extra = {k: v for k, v in self.attrs.items()
                     if isinstance(v, (int, float, str))
                     and k not in ("name", "trace", "dur_ns",
                                   "kind", "seq", "t", "ns")}
            _flight.record("span", name=self.name, trace=self.trace_id,
                           dur_ns=dur, **extra)
        _metrics.note_span(self.name, dur)

    # -- context-manager form: nest via the thread-local context
    def __enter__(self):
        self._prev_ctx = getattr(_TLS, "ctx", None)
        _TLS.ctx = self.ctx
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb):
        _TLS.ctx = self._prev_ctx
        if exc_type is not None and "error" not in self.attrs:
            self.attrs["error"] = exc_type.__name__
        self.end()
        return False


class _NoopSpan:
    """Shared do-nothing span: the entire disabled-path cost of an
    instrumented site is building this module's function call and one
    global check."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    ctx = None

    def set(self, **attrs):
        return self

    def end(self, **attrs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopSpan()


def _tracing_here():
    """Tracing is live on this thread: globally enabled, or force-traced
    by a shipped context (a process replica serving a traced request
    while its own global flag is off)."""
    return _ENABLED or getattr(_TLS, "force", False)


def span(name, **attrs):
    """Open one span as a context manager, parented under the calling
    thread's current context. No-op (shared instance) when tracing is
    off — safe to leave on every hot path."""
    if not _tracing_here():
        return _NOOP
    return Span(name, current(), attrs)


def start_span(name, parent=None, **attrs):
    """Open a span WITHOUT touching the thread-local context — for
    lifetimes that end on another thread (the router's per-request and
    per-attempt spans end in future callbacks). ``parent`` is a
    ``(trace_id, span_id)`` context; None parents under the caller's
    current context (or roots a new trace)."""
    if not _tracing_here():
        return _NOOP
    return Span(name, parent if parent is not None else current(), attrs)


def record(name, t0_ns, dur_ns, parent=None, **attrs):
    """Record a span retroactively from measured timestamps (the
    batcher's batch-form wait is only known once the batch pops)."""
    if not _tracing_here():
        return
    ctx = parent if parent is not None else current()
    if ctx is None:
        trace_id, parent_id = new_trace_id(), None
    else:
        trace_id, parent_id = ctx
    _store({"trace": trace_id, "span": _new_span_id(),
            "parent": parent_id, "name": name, "t0_ns": int(t0_ns),
            "dur_ns": int(dur_ns),
            "thread": threading.current_thread().name, "attrs": attrs})


def _store(rec):
    with _RING_LOCK:
        _RING.append(rec)
    _STATS["obs_spans"] += 1
    col = getattr(_TLS, "collect", None)
    if col is not None:
        col.append(rec)


@contextlib.contextmanager
def context(ctx, force=False):
    """Re-enter a captured context on this thread (cross-thread
    propagation). ``force=True`` additionally turns tracing on for the
    duration — a process replica serving a traced request must record
    spans even though its own ``MXNET_TPU_OBS_TRACE`` may be unset."""
    prev = getattr(_TLS, "ctx", None)
    prev_force = getattr(_TLS, "force", False)
    _TLS.ctx = ctx
    if force:
        _TLS.force = True
    try:
        yield
    finally:
        _TLS.ctx = prev
        _TLS.force = prev_force


@contextlib.contextmanager
def collect():
    """Collect every span record ended on this thread while the block
    runs (nested consumers stack). The fleet's process-replica worker
    wraps each request in this and ships the collected records back with
    the reply; the parent feeds them to :func:`ingest`."""
    prev = getattr(_TLS, "collect", None)
    col = []
    _TLS.collect = col
    try:
        yield col
    finally:
        _TLS.collect = prev
        if prev is not None:
            prev.extend(col)


def ingest(records):
    """Merge span records produced in another process (shipped over the
    replica pipe) into the local ring so ``spans()`` shows one connected
    tree per trace id."""
    n = 0
    with _RING_LOCK:
        for rec in records or ():
            if isinstance(rec, dict) and "span" in rec and "name" in rec:
                _RING.append(rec)
                n += 1
    _STATS["obs_spans_shipped"] += n
    return n


def spans(trace_id=None, name=None):
    """Snapshot of the ended-span ring (insertion order), optionally
    filtered by trace id and/or span name."""
    with _RING_LOCK:
        out = list(_RING)
    if trace_id is not None:
        out = [s for s in out if s["trace"] == trace_id]
    if name is not None:
        out = [s for s in out if s["name"] == name]
    return out


def roots(names=()):
    """Root-span records (``parent is None``) currently in the ring,
    optionally restricted to a set of span names — the entry points
    incident exemplars and timeline exports start from."""
    names = set(names)
    return [s for s in spans()
            if s["parent"] is None and (not names or s["name"] in names)]


def clear():
    with _RING_LOCK:
        _RING.clear()
