"""Chrome-trace timeline export: span records -> Trace Event Format.

``to_chrome_trace()`` converts collected span records (the
``trace.spans()`` ring, a crash report's exemplar trees, or any list of
span dicts) into the JSON Trace Event Format that loads directly in
Perfetto / ``chrome://tracing``:

- every span becomes one complete (``"ph": "X"``) event with
  microsecond ``ts``/``dur``;
- **pid** comes from the span-id's process prefix (span ids are minted
  as ``<pid-hex>.<counter>``, so records shipped back over the fleet's
  process-replica pipe keep their origin identity) and **tid** from the
  recording thread's name, with ``process_name`` / ``thread_name``
  metadata events so the timeline reads "replica 1" and
  "mxnet-tpu-serving", not bare numbers;
- cross-process clock skew is handled structurally: each foreign
  process's events are shifted so its earliest span whose *parent*
  lives in another process starts just inside that parent
  (``perf_counter_ns`` epochs are per-process and otherwise
  incomparable), keeping the fleet tree visually nested.

Deliberately self-contained (stdlib only, no package-relative imports)
so ``tools/trace_export.py`` can load it by file path and convert an
existing dump/crash-report JSON without importing the runtime (or
jax). ``tools/trace_export.py`` is the CLI; incidents embed the
timeline of their exemplar trees (docs/observability.md, "Timeline
export").
"""
from __future__ import annotations

import os

__all__ = ["to_chrome_trace", "span_pid"]

# one synthetic nesting margin (ns) when re-basing a foreign process's
# clock inside its cross-process parent span
_ALIGN_MARGIN_NS = 1000


def span_pid(record):
    """The origin-process id of one span record, parsed from the
    span-id's ``<pid-hex>.<counter>`` prefix; 0 when unparsable."""
    sid = str(record.get("span", ""))
    head = sid.split(".", 1)[0]
    try:
        return int(head, 16)
    except ValueError:
        return 0


def _process_offsets(records, by_id):
    """ns offset to add per foreign pid so each process's events sit
    inside their cross-process parent span (clock re-basing)."""
    home = os.getpid()
    offsets = {}
    for rec in records:
        pid = span_pid(rec)
        if pid == home or pid in offsets:
            continue
        parent = by_id.get(rec.get("parent"))
        if parent is None or span_pid(parent) == pid:
            continue
        offsets[pid] = (parent["t0_ns"] + _ALIGN_MARGIN_NS) - rec["t0_ns"]
    return offsets


def to_chrome_trace(records=None):
    """Convert span records to a Trace Event Format dict
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``) —
    ``json.dump`` it and load the file in Perfetto. ``records``
    defaults to the live ``trace.spans()`` ring (which requires the
    package; explicit records keep this module standalone)."""
    if records is None:
        from . import trace as _trace

        records = _trace.spans()
    records = [r for r in records
               if isinstance(r, dict) and "span" in r and "t0_ns" in r]
    by_id = {r["span"]: r for r in records}
    offsets = _process_offsets(records, by_id)

    events = []
    # (pid, thread-name) -> tid; tid 1..N per process, stable by first
    # appearance so re-exports of the same records agree
    tids: dict = {}
    proc_names: dict = {}
    for rec in records:
        pid = span_pid(rec)
        thread = str(rec.get("thread", "?"))
        key = (pid, thread)
        if key not in tids:
            tids[key] = sum(1 for k in tids if k[0] == pid) + 1
        name = str(rec.get("name", "?"))
        attrs = rec.get("attrs") or {}  # tolerate an explicit null in
        if name == "serve.replica" and "replica" in attrs:  # foreign JSON
            proc_names[pid] = f"replica {attrs['replica']}"
        t0 = rec["t0_ns"] + offsets.get(pid, 0)
        args = {"trace": rec.get("trace"), "span": rec["span"]}
        if rec.get("parent") is not None:
            args["parent"] = rec["parent"]
        for k, v in attrs.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                args[k] = v
        events.append({
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": t0 / 1e3,
            "dur": max(0.001, (rec.get("dur_ns") or 0) / 1e3),
            "pid": pid,
            "tid": tids[key],
            "args": args,
        })

    home = os.getpid()
    meta = []
    for pid in sorted({p for p, _ in tids}):
        label = proc_names.get(pid) or (
            "main" if pid == home else f"process {pid:#x}")
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": label}})
    for (pid, thread), tid in sorted(tids.items(),
                                     key=lambda kv: (kv[0][0], kv[1])):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": thread}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
