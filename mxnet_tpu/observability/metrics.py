"""Typed metrics registry with labels, time series, and two exporters.

The runtime's flat ``_STATS`` dicts (profiler.dispatch_stats()) are
point-in-time int counters: good forensics, not operable telemetry —
no types, no labels, no history, no export format. This registry
generalizes them:

- **Instruments**: :func:`counter` (monotonic), :func:`gauge`
  (set/observe last value), :func:`histogram` (bucketed distribution
  with sum/count). Each takes a label-name tuple; every recorded value
  addresses one labelset (``c.inc(1, model="resnet")``).
- **Time series**: :func:`sample` appends one snapshot of every
  instrument to a bounded ring (``MXNET_TPU_METRICS_RING``, default
  512 samples) — enough history for a dashboard to draw a line without
  an external store.
- **Exporters**: :func:`render_prometheus` produces text exposition
  (typed instruments first, then every ``profiler.dispatch_stats()``
  counter as ``mxnet_tpu_<name>``, which is how the legacy flat
  counters ride along for free); :func:`flush_json` appends one
  JSON-lines record to ``MXNET_TPU_METRICS_FILE`` (a background daemon
  flusher runs on a ``MXNET_TPU_METRICS_FLUSH_S`` cadence once
  :func:`start_flusher` arms it — automatically at first registry
  write when the file knob is set). :func:`serve_http` exposes
  ``/metrics`` from a stdlib http.server daemon thread
  (``MXNET_TPU_METRICS_PORT``).
- **Fleet SLO derivation**: :func:`update_slo` refreshes the
  ``mxnet_tpu_fleet_*`` gauges below from the live serving fleet
  (per-model deadline hit-rate, shed rate, p50/p99 latency, breaker
  and replica health states) — every exporter calls it, so SLO series
  exist without any caller wiring.

Every metric name registered through this module must be documented in
docs/observability.md — graftlint's RD004 pass enforces it (the same
drift guard RD001 applies to env knobs). Stdlib-only at import.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time

from collections import deque

from . import _STATS

__all__ = ["counter", "gauge", "histogram", "get", "registry",
           "snapshot", "sample", "series", "render_prometheus",
           "flush_json", "start_flusher", "stop_flusher", "serve_http",
           "update_slo", "update_decode_slo", "update_input_stall",
           "update_pod", "update_derived", "slo_counters",
           "decode_counters",
           "note_span", "reset", "Counter", "Gauge", "Histogram"]

_LOCK = threading.Lock()
_REGISTRY: dict = {}

try:
    _SERIES_SIZE = int(os.environ.get("MXNET_TPU_METRICS_RING", "512"))
except ValueError:
    _SERIES_SIZE = 512
_SERIES = deque(maxlen=max(1, _SERIES_SIZE))

# default latency-style buckets (milliseconds)
DEFAULT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 10000.0)


def _labelset(labels, values):
    if set(values) != set(labels):
        raise ValueError(
            f"metric labels are {sorted(labels)}, got {sorted(values)}")
    return tuple((k, str(values[k])) for k in labels)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help, labels):
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        self._lock = threading.Lock()
        self._data: dict = {}

    def labelsets(self):
        with self._lock:
            return list(self._data)

    def value(self, **labels):
        with self._lock:
            return self._data.get(_labelset(self.labels, labels))

    def remove(self, **labels):
        """Drop one labelset's cell (derived gauges prune series whose
        subject — a replica, an executable — no longer exists, so
        exporters don't accrete unbounded label cardinality and stale
        frozen values)."""
        with self._lock:
            self._data.pop(_labelset(self.labels, labels), None)

    def _snapshot(self):
        with self._lock:
            return dict(self._data)

    def _reset(self):
        with self._lock:
            self._data.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, value=1, **labels):
        if value < 0:
            raise ValueError("counters are monotonic; use a gauge")
        key = _labelset(self.labels, labels)
        with self._lock:
            self._data[key] = self._data.get(key, 0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, **labels):
        key = _labelset(self.labels, labels)
        with self._lock:
            self._data[key] = value

    def inc(self, value=1, **labels):
        key = _labelset(self.labels, labels)
        with self._lock:
            self._data[key] = self._data.get(key, 0) + value


class Histogram(_Metric):
    """Bucketed distribution. Internal bucket counts are PER-BUCKET
    (non-cumulative), one extra overflow slot at the end — one bisect +
    one increment per observe, the hot-path shape; the Prometheus
    renderer produces the cumulative ``le`` form."""

    kind = "histogram"

    def __init__(self, name, help, labels, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _snapshot(self):
        # deep-copy each cell UNDER the lock: the generic shallow copy
        # would hand exporters live cell dicts, and a renderer iterating
        # `buckets` while observes land could emit a torn distribution
        # (cumulative buckets exceeding `count`). One consistent point
        # snapshot keeps the rendered cumulative series monotone with
        # `le="+Inf"` == count by construction, even under racing
        # observes (regression-tested).
        with self._lock:
            return {k: {"count": c["count"], "sum": c["sum"],
                        "buckets": list(c["buckets"])}
                    for k, c in self._data.items()}

    def _cell(self, key):
        cell = self._data.get(key)
        if cell is None:
            cell = {"count": 0, "sum": 0.0,
                    "buckets": [0] * (len(self.buckets) + 1)}
            self._data[key] = cell
        return cell

    def observe(self, value, **labels):
        key = _labelset(self.labels, labels)
        value = float(value)
        with self._lock:
            cell = self._cell(key)
            cell["count"] += 1
            cell["sum"] += value
            cell["buckets"][bisect.bisect_left(self.buckets, value)] += 1

    def percentile(self, q, **labels):
        """Approximate percentile from the bucket boundaries (the
        upper edge of the bucket the q-quantile falls in); None when
        the labelset has no observations."""
        cell = self.value(**labels)
        if not cell or not cell["count"]:
            return None
        rank = q * cell["count"]
        seen = 0
        for i, le in enumerate(self.buckets):
            seen += cell["buckets"][i]
            if seen >= rank:
                return le
        return float("inf")


def _register(cls, name, help, labels, **kw):
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is not None:
            if type(m) is not cls or tuple(labels) != m.labels:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind} with "
                    f"labels {m.labels}")
            return m
        m = cls(name, help, labels, **kw)
        _REGISTRY[name] = m
        return m


def counter(name, help="", labels=()):
    """Register (idempotently) and return a monotonic Counter."""
    return _register(Counter, name, help, labels)


def gauge(name, help="", labels=()):
    return _register(Gauge, name, help, labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS):
    return _register(Histogram, name, help, labels, buckets=buckets)


def get(name):
    with _LOCK:
        return _REGISTRY.get(name)


def registry():
    with _LOCK:
        return dict(_REGISTRY)


def reset():
    """Zero every instrument's data (registrations survive — the
    catalog is code structure, the values are run state)."""
    for m in registry().values():
        m._reset()
    with _LOCK:
        _SERIES.clear()
        _SPAN_CELLS.clear()


# -------------------------------------------------------- built-in series

# The span-duration histogram every ended span feeds (trace.py): one
# instrument, one label — the span name — so the whole span taxonomy is
# exportable without a registration per instrumentation site.
_SPAN_MS = histogram(
    "mxnet_tpu_span_ms",
    "duration of ended trace spans, by span name", labels=("name",))

# Fleet SLO gauges, derived from the live serving layer by update_slo().
_SLO_HIT_RATE = gauge(
    "mxnet_tpu_fleet_deadline_hit_rate",
    "fraction of admitted fleet requests not lost to their deadline")
_SLO_SHED_RATE = gauge(
    "mxnet_tpu_fleet_shed_rate",
    "fraction of admitted fleet requests shed as overloaded")
_SLO_P50 = gauge("mxnet_tpu_fleet_p50_us",
                 "fleet request latency p50 (us)", labels=("model",))
_SLO_P99 = gauge("mxnet_tpu_fleet_p99_us",
                 "fleet request latency p99 (us)", labels=("model",))
_SLO_BREAKER = gauge(
    "mxnet_tpu_fleet_breaker_open",
    "1 when the replica's circuit breaker is open",
    labels=("model", "replica"))
_SLO_HEALTHY = gauge(
    "mxnet_tpu_fleet_healthy_replicas",
    "replicas currently in HEALTHY rotation", labels=("model",))

# Decode-streaming SLO gauges, derived from the serving layer's
# TTFT/ITL sliding windows by update_decode_slo() on the same exporter
# cadence as the fleet family (docs/decode.md: TTFT and inter-token
# latency are decode's two first-class latencies).
_DECODE_TTFT_P50 = gauge(
    "mxnet_tpu_decode_ttft_p50_us",
    "decode time-to-first-token p50 (us), sliding window")
_DECODE_TTFT_P99 = gauge(
    "mxnet_tpu_decode_ttft_p99_us",
    "decode time-to-first-token p99 (us), sliding window")
_DECODE_ITL_P99 = gauge(
    "mxnet_tpu_decode_itl_p99_us",
    "decode inter-token latency p99 (us), sliding window")
_DECODE_TTFT_HIT = gauge(
    "mxnet_tpu_decode_ttft_hit_rate",
    "fraction of admitted decode sequences whose first token met the "
    "TTFT SLO (MXNET_TPU_DECODE_TTFT_SLO_MS)")


def _ratio(num, den):
    """num/den with the zero-denominator edge pinned to 0.0 — a derived
    rate over an empty window must export 0 (or stay absent), never NaN
    or a ZeroDivisionError that kills the exporter thread."""
    return num / den if den else 0.0


def slo_counters():
    """The cumulative fleet SLO counter triple (requests, deadline
    misses, overload sheds) every SLO consumer — :func:`update_slo`'s
    gauges and the alert engine's burn-rate windows — reads, with the
    ``slo_burn`` fault hook applied upstream of both: the chaos drill
    inflates deadline misses HERE, so the injected burn flows through
    the real derivation and window math, never a shortcut."""
    try:
        from .. import serving
    except Exception:
        return {}
    counters = {
        "fleet_requests": serving._STATS["fleet_requests"],
        "fleet_deadline_exceeded":
            serving._STATS["fleet_deadline_exceeded"],
        "fleet_shed_overloaded": serving._STATS["fleet_shed_overloaded"],
    }
    try:
        from ..resilience import faults
    except Exception:
        return counters
    return faults.maybe_slo_burn(counters)


def update_slo(counters=None):
    """Refresh the ``mxnet_tpu_fleet_*`` gauges from the live serving
    layer. Called by every exporter; safe (and cheap) with no fleet.
    Division edges are explicit: a zero-request window leaves the rate
    gauges absent (no data is not a 0% hit rate), an empty fleet or a
    model with zero replicas reports 0 healthy replicas and 0-latency
    percentiles rather than NaN. Per-model/replica labelsets whose
    subject left the live fleet set are pruned (the
    ``perf.update_gauges`` discipline) so a closed fleet's breaker
    cell cannot export ``open=1`` forever. ``counters`` reuses a
    :func:`slo_counters` view already taken this tick
    (``update_derived`` passes one shared view to the gauges AND the
    alert engine, so a bounded-``times`` ``slo_burn`` arm inflates
    both identically instead of burning one fire per consumer)."""
    try:
        from .. import serving
    except Exception:
        return
    if counters is None:
        counters = slo_counters()
    s_requests = counters.get("fleet_requests", 0)
    if s_requests > 0:
        _SLO_HIT_RATE.set(1.0 - _ratio(
            counters["fleet_deadline_exceeded"], s_requests))
        _SLO_SHED_RATE.set(_ratio(
            counters["fleet_shed_overloaded"], s_requests))
    live_models = set()
    live_replicas = set()
    for fleet in serving._live_fleets():
        try:
            models = fleet.models()
        except Exception:
            continue
        for model in models:
            lat = []
            healthy = 0
            live_models.add(str(model))
            try:
                replicas = fleet._sup.replicas(model)
            except Exception:
                replicas = ()  # a closing fleet's model set can race its
            for r in replicas:  # supervisor teardown: report empty, not die
                lat.extend(r.latency_snapshot())
                healthy += 1 if r.state == "HEALTHY" else 0
                live_replicas.add((str(model), str(r.rid)))
                _SLO_BREAKER.set(1 if r.breaker.is_open else 0,
                                 model=model, replica=r.rid)
            _SLO_HEALTHY.set(healthy, model=model)
            lat.sort()
            # _percentile_us returns 0 for an empty window by contract
            _SLO_P50.set(serving._percentile_us(lat, 0.50), model=model)
            _SLO_P99.set(serving._percentile_us(lat, 0.99), model=model)
    for labelset in _SLO_BREAKER.labelsets():
        d = dict(labelset)
        if (d.get("model"), d.get("replica")) not in live_replicas:
            _SLO_BREAKER.remove(model=d.get("model"),
                                replica=d.get("replica"))
    for g in (_SLO_HEALTHY, _SLO_P50, _SLO_P99):
        for labelset in g.labelsets():
            model = dict(labelset).get("model")
            if model not in live_models:
                g.remove(model=model)


def decode_counters():
    """The decode SLO counter pair (admitted sequences, TTFT SLO
    misses) the ``decode_ttft_burn`` alert rule windows — read from the
    same ``serving._STATS`` the gauges derive from, and empty until the
    serving layer has been imported (same light-process discipline as
    the fleet counters)."""
    import sys

    serving = sys.modules.get("mxnet_tpu.serving")
    if serving is None:
        return {}
    return {
        "decode_sequences": serving._STATS["decode_sequences"],
        "decode_ttft_misses": serving._STATS["decode_ttft_misses"],
    }


def update_decode_slo():
    """Refresh the ``mxnet_tpu_decode_*`` gauges from the serving
    layer's TTFT/ITL sliding windows. Cheap and safe with no decode
    traffic: empty windows leave the percentile gauges absent (no data
    is not a 0 us TTFT) and a zero-sequence run leaves the hit-rate
    gauge absent rather than claiming a perfect SLO."""
    import sys

    serving = sys.modules.get("mxnet_tpu.serving")
    if serving is None:
        return
    with serving._LAT_LOCK:
        ttft = sorted(serving._TTFT)
        itl = sorted(serving._ITL)
    if ttft:
        _DECODE_TTFT_P50.set(serving._percentile_us(ttft, 0.50))
        _DECODE_TTFT_P99.set(serving._percentile_us(ttft, 0.99))
    if itl:
        _DECODE_ITL_P99.set(serving._percentile_us(itl, 0.99))
    seqs = serving._STATS["decode_sequences"]
    if seqs > 0:
        _DECODE_TTFT_HIT.set(1.0 - _ratio(
            serving._STATS["decode_ttft_misses"], seqs))


# ------------------------------------------- derived training-input gauge

# ROADMAP item 3's gate signal: the fraction of training-loop wall time
# spent stalled on the input pipeline, derived from the span ring the
# same way update_slo derives fleet gauges — no caller wiring.
_INPUT_STALL = gauge(
    "mxnet_tpu_input_stall_fraction",
    "step.data_wait time / observed training-window wall time (first "
    "span start to last span end over data_wait + training-step root "
    "spans in the ring); 0 when the window has no training spans")

_STEP_ROOT_SPANS = ("train.step", "train.sharded_step",
                    "train.captured_step")


def update_input_stall():
    """Derive ``mxnet_tpu_input_stall_fraction`` from the ended-span
    ring: time inside ``step.data_wait`` spans over the **wall-clock
    window** those training spans cover (earliest start to latest end
    across data_wait + step-root spans). The wall window — not the sum
    of span durations — is the denominator because the eager path's
    forward/backward runs in user code no span covers: ``train.step``
    only spans the update phases there, and a sum-of-spans denominator
    would report a compute-bound eager job as input-stalled. Requires
    tracing on (``MXNET_TPU_OBS_TRACE``) to have data; an empty window
    reports 0.0 — never NaN."""
    from . import trace as _trace

    wait = 0
    t_min = None
    t_max = None
    for s in _trace.spans():
        if s["name"] == "step.data_wait":
            wait += s["dur_ns"]
        elif s["name"] not in _STEP_ROOT_SPANS:
            continue
        t_min = s["t0_ns"] if t_min is None else min(t_min, s["t0_ns"])
        end = s["t0_ns"] + s["dur_ns"]
        t_max = end if t_max is None else max(t_max, end)
    window = (t_max - t_min) if t_min is not None else 0
    value = min(1.0, _ratio(wait, window))
    _INPUT_STALL.set(value)
    return value


# --------------------------------------------------- derived pod gauges

# Pod liveness view, derived from the watchdog's host-domain tracker by
# update_pod(): ONE aggregated picture of the whole pod on every host's
# exporter, so the alert engine fires host-down alerts from any
# survivor even while the dead host's own exporter is gone.
_POD_HOSTS = gauge(
    "mxnet_tpu_pod_hosts",
    "hosts in the pod's current topology (absent when no pod is "
    "configured)")
_POD_HOSTS_LIVE = gauge(
    "mxnet_tpu_pod_hosts_live",
    "pod hosts not currently marked dead by the watchdog liveness layer")
_POD_HOST_UP = gauge(
    "mxnet_tpu_pod_host_up",
    "1 while the labeled pod host rank is live, 0 once the watchdog "
    "marks it dead (sticky until re-admission)", labels=("host",))


def update_pod():
    """Refresh the ``mxnet_tpu_pod_*`` gauges from the watchdog's pod
    snapshot. A process that never configured a pod leaves every pod
    series absent (a single-host run has no pod, not a pod of one);
    after an elastic shrink the renumbered topology's host series
    replace the old ones so cardinality tracks the live pod."""
    import sys

    watchdog = sys.modules.get("mxnet_tpu.resilience.watchdog")
    if watchdog is None:
        return None
    snap = watchdog.pod_snapshot()
    if not snap.get("configured"):
        for h in list(_POD_HOST_UP.labelsets()):
            _POD_HOST_UP.remove(**dict(h))
        return None
    num = int(snap["num_hosts"])
    dead = set(snap["dead_hosts"])
    _POD_HOSTS.set(num)
    _POD_HOSTS_LIVE.set(num - len(dead & set(range(num))))
    current = {str(h) for h in range(num)}
    for ls in list(_POD_HOST_UP.labelsets()):
        if dict(ls).get("host") not in current:
            _POD_HOST_UP.remove(**dict(ls))
    for h in range(num):
        _POD_HOST_UP.set(0.0 if h in dead else 1.0, host=h)
    return snap


def update_derived():
    """Refresh every auto-derived gauge family — fleet SLO, input-stall
    fraction, and the per-executable perf-ledger gauges — in one place,
    then give the alert engine its evaluation tick. Every exporter
    calls this, so derived series exist — and alert rules run — on the
    exporter cadence without any caller wiring. One ``slo_counters()``
    view is taken per tick and shared between the SLO gauges and the
    alert windows (one ``slo_burn`` hook fire per tick, identical
    inflated view on both sides)."""
    counters = slo_counters()
    update_slo(counters)
    update_decode_slo()
    update_pod()
    stall = update_input_stall()
    from . import perf as _perf

    _perf.update_gauges()
    from . import alerts as _alerts

    _alerts.maybe_evaluate(slo=counters, input_stall=stall)


# per-span-name cell cache for the note_span hot path: skips the
# labelset validation + dict churn of the generic observe() — a traced
# training step ends a handful of spans per millisecond
_SPAN_CELLS: dict = {}


def note_span(name, dur_ns):
    """Trace hook: one ended span -> one histogram observation (the
    fast path of ``mxnet_tpu_span_ms.observe(..., name=name)``)."""
    cell = _SPAN_CELLS.get(name)
    if cell is None:
        # create + cache under ONE registry-lock hold: reset() clears
        # the instrument data first and the cache second (also under
        # _LOCK), so a cell detached by a concurrent reset is always
        # evicted from the cache too — never a ghost cell silently
        # swallowing every later observation of this span name
        with _LOCK:
            with _SPAN_MS._lock:
                cell = _SPAN_MS._cell((("name", str(name)),))
            _SPAN_CELLS[name] = cell
    value = dur_ns / 1e6
    with _SPAN_MS._lock:
        cell["count"] += 1
        cell["sum"] += value
        cell["buckets"][bisect.bisect_left(_SPAN_MS.buckets, value)] += 1


# ------------------------------------------------------------- snapshots

def _escape_label(value):
    """Prometheus text-format label-value escaping (\\ " and newline) —
    one hostile model/tensor name must not fail the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _flat_key(name, labelset):
    if not labelset:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labelset)
    return f"{name}{{{inner}}}"


def snapshot():
    """Every instrument's current data as one JSON-friendly dict:
    ``{name: {"kind", "labels", "values": {flat-label-key: value}}}``
    (histogram values are ``{count, sum, buckets}``)."""
    update_derived()
    out = {}
    for name, m in sorted(registry().items()):
        values = {}
        for labelset, v in m._snapshot().items():
            values[_flat_key("", labelset) or ""] = v
        out[name] = {"kind": m.kind, "labels": list(m.labels),
                     "values": values}
        if isinstance(m, Histogram):
            out[name]["buckets"] = list(m.buckets)
    return out


def sample(now=None):
    """Append one time-series sample of every instrument (and the SLO
    gauges) to the ring; returns the sample. Each record carries BOTH
    clocks (docs/observability.md, "time-series record schema"):
    wall-clock ``t`` (epoch seconds, for humans and dashboards) and
    monotonic ``ns`` (``perf_counter_ns``, what windowed consumers
    like the alert engine difference — wall clock can step)."""
    rec = {"t": time.time() if now is None else now,
           "ns": time.perf_counter_ns(),
           "metrics": snapshot()}
    with _LOCK:
        _SERIES.append(rec)
    _STATS["obs_metric_samples"] += 1
    return rec


def series():
    """The ring-buffered time series, oldest first."""
    with _LOCK:
        return list(_SERIES)


# ------------------------------------------------------------- exporters

def render_prometheus(include_runtime_counters=True):
    """Prometheus text exposition (format 0.0.4): the typed registry
    first, then — unless disabled — every numeric
    ``profiler.dispatch_stats()`` counter as an untyped
    ``mxnet_tpu_<name>`` sample, which is how the runtime's flat
    counters export without per-counter registration."""
    update_derived()
    lines = []
    for name, m in sorted(registry().items()):
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        lines.append(f"# TYPE {name} {m.kind}")
        data = m._snapshot()
        if isinstance(m, Histogram):
            for labelset, cell in sorted(data.items()):
                cum = 0
                for le, n in zip(m.buckets, cell["buckets"]):
                    cum += n
                    key = _flat_key(name + "_bucket",
                                    labelset + (("le", f"{le:g}"),))
                    lines.append(f"{key} {cum}")
                key = _flat_key(name + "_bucket",
                                labelset + (("le", "+Inf"),))
                lines.append(f"{key} {cell['count']}")
                lines.append(
                    f"{_flat_key(name + '_sum', labelset)} {cell['sum']:g}")
                lines.append(
                    f"{_flat_key(name + '_count', labelset)} "
                    f"{cell['count']}")
        else:
            for labelset, v in sorted(data.items()):
                lines.append(f"{_flat_key(name, labelset)} {v:g}")
    if include_runtime_counters:
        try:
            from .. import profiler

            counters = profiler.dispatch_stats()
        except Exception:
            counters = {}
        for k, v in sorted(counters.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue  # e.g. fleet_replica_latency_us is a summary str
            lines.append(f"# TYPE mxnet_tpu_{k} untyped")
            lines.append(f"mxnet_tpu_{k} {v:g}")
    return "\n".join(lines) + "\n"


def metrics_file():
    return os.environ.get("MXNET_TPU_METRICS_FILE", "").strip() or None


def flush_json(path=None, include_runtime_counters=True, record=None):
    """Append one JSON-lines record — timestamp, the typed-metric
    snapshot, and (by default) the flat runtime counters — to ``path``
    (default ``MXNET_TPU_METRICS_FILE``). Returns the path, or None
    when no destination is configured. ``record`` reuses a snapshot
    already taken (the background flusher passes its ``sample()`` so
    each cycle walks the registry/fleet once, not twice)."""
    path = path or metrics_file()
    if not path:
        return None
    rec = dict(record) if record is not None \
        else {"t": time.time(), "metrics": snapshot()}
    if include_runtime_counters:
        try:
            from .. import profiler

            rec["counters"] = profiler.dispatch_stats()
        except Exception:
            pass
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec, default=str) + "\n")
    _STATS["obs_metric_flushes"] += 1
    return path


# ------------------------------------------------- background flusher/http

_FLUSHER = None
_FLUSHER_STOP = None


def flush_cadence_s():
    raw = os.environ.get("MXNET_TPU_METRICS_FLUSH_S", "").strip()
    try:
        v = float(raw) if raw else 10.0
    except ValueError:
        v = 10.0
    return max(0.05, v)


def start_flusher(path=None, cadence_s=None):
    """Start (idempotently) the background JSON-lines flusher daemon:
    every ``cadence_s`` (default ``MXNET_TPU_METRICS_FLUSH_S``, 10 s)
    it takes a time-series :func:`sample` and appends one line to the
    metrics file. No-op when no file is configured. Returns True when
    a flusher is (now) running."""
    global _FLUSHER, _FLUSHER_STOP
    path = path or metrics_file()
    if not path:
        return False
    with _LOCK:
        if _FLUSHER is not None and _FLUSHER.is_alive():
            return True
        stop = threading.Event()

        def loop():
            while not stop.wait(cadence_s or flush_cadence_s()):
                try:
                    flush_json(path, record=sample())
                except Exception:
                    pass  # the exporter must never take the run down
            try:
                # final flush so short runs export too
                flush_json(path, record=sample())
            except Exception:
                pass

        t = threading.Thread(target=loop, name="mxnet-tpu-metrics-flush",
                             daemon=True)
        _FLUSHER, _FLUSHER_STOP = t, stop
    t.start()
    return True


def stop_flusher(timeout=2.0):
    """Stop the background flusher (one final flush included)."""
    global _FLUSHER, _FLUSHER_STOP
    with _LOCK:
        t, stop = _FLUSHER, _FLUSHER_STOP
        _FLUSHER = _FLUSHER_STOP = None
    if stop is not None:
        stop.set()
    if t is not None and t.is_alive():
        t.join(timeout)


def maybe_start_flusher():
    """Arm the background flusher iff ``MXNET_TPU_METRICS_FILE`` is
    set — called from the instrumented runtime's first touch points so
    an operator only needs the env knob."""
    if metrics_file():
        start_flusher()


def serve_http(port=None, host="127.0.0.1"):
    """Serve Prometheus text exposition at ``/metrics`` (and a JSON
    dump at ``/obs``) from a stdlib ThreadingHTTPServer daemon thread.
    ``port`` defaults to ``MXNET_TPU_METRICS_PORT`` (0/unset = do not
    serve, returns None). Returns the live server (``.server_port``,
    ``.shutdown()``)."""
    if port is None:
        raw = os.environ.get("MXNET_TPU_METRICS_PORT", "").strip()
        if not raw:
            return None
        port = int(raw)
        if port < 0:
            return None
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/metrics"):
                body = render_prometheus().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path.startswith("/obs"):
                from . import dump

                body = json.dumps(dump(), default=str).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # no stderr chatter from scrapes
            pass

    server = ThreadingHTTPServer((host, int(port)), Handler)
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever,
                         name="mxnet-tpu-metrics-http", daemon=True)
    t.start()
    return server
