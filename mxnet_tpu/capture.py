"""Whole-program step capture + persistent AOT compile cache.

ROADMAP item 3 (the Julia-to-TPU full-compilation argument, PAPERS.md
[1810.09868], and TensorFlow's whole-graph compilation [1605.08695]):
instead of eager dispatch with bulked segments, compile the *entire*
training step — forward, backward, optimizer update sweep, and the
HealthSentinel/loss-scaler finite check — into ONE donated XLA
executable, and serialize compiled programs to disk so a new process
(serving cold-start, multi-host restart) skips XLA compilation.

Three layers, all routed through the single sanctioned compile site
``_compile_jit`` (graftlint TS002):

1. **Capture** — :func:`capture` turns a gluon ``Trainer`` step (the
   eager fwd/bwd + bulked-update hot loop) or a parallel
   ``ShardedTrainer`` into a captured step object. The gluon capture
   re-runs the user's imperative step under trace via the
   mutation->functional bridge (``jit.TraceSession``), with three
   properties the plain ``mx.jit.trace`` path lacks:

   - **dynamic scalar operands**: every hyperparameter an optimizer op
     declares ``dynamic_params`` for (lr, wd, rescale_grad — including
     schedule- and bias-correction-drifted values) is a runtime operand,
     refreshed each step by a *scalar replay* of the update sweep's
     Python (array math skipped), so an Adam bias correction or lr
     schedule neither retraces nor goes stale;
   - **fused sentinel check**: with a HealthSentinel attached, one
     ``multi_all_finite`` reduction over the gradients runs *inside*
     the program and gates every weight/state write with a select, so
     an unhealthy batch never touches the weights — policies
     (raise/skip_batch/rollback) apply on the host from the returned
     flag exactly as on the eager path;
   - **retrace forensics**: a signature change (shape, dtype, scalar
     slots, rebound trainer state) bumps ``capture_retraces``, records
     a structured reason in the dispatch ring (crash reports embed it)
     and in :func:`retrace_log` — never a silent recompile.

2. **CapturedExec** — the keyed executable wrapper the
   ``ShardedTrainer`` fused/elastic steps and the serving ``Predictor``
   bucket executables compile through: per-signature executable cache,
   the same forensics, and the AOT layer below.

3. **AOT compile cache** — with ``MXNET_TPU_COMPILE_CACHE=<dir>``,
   compiled programs are persisted as ``jax.export`` artifacts keyed by
   (program fingerprint, avals/sharding/donation signature, backend
   topology) with the jax/jaxlib versions in the header, next to jax's
   persistent XLA executable cache (``<dir>/xla``). A warm process
   deserializes the traced program (skipping Python tracing + lowering)
   and re-links the XLA executable from the persistent cache (skipping
   XLA compilation). Stale (version-mismatched) and corrupt artifacts
   fall back to a fresh compile — never a crash.

Env knobs (docs/env_vars.md): ``MXNET_TPU_CAPTURE``,
``MXNET_TPU_COMPILE_CACHE``, ``MXNET_TPU_COMPILE_CACHE_MAX_MB``,
``MXNET_TPU_COMPILE_CACHE_SALT``. Counters surface in
``profiler.dispatch_stats()``. See docs/capture.md.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from . import profiler as _profiler
from .observability import flight as _obs_flight
from .observability import numerics as _obs_numerics
from .observability import perf as _obs_perf
from .observability import trace as _obs_trace

__all__ = ["capture", "CapturedTrainerStep", "CapturedShardedStep",
           "CapturedExec", "CaptureError", "enabled", "aot_enabled",
           "cache_dir", "compile_cache", "aot_compile", "note_recapture",
           "retrace_log", "clear_retrace_log", "stats", "reset_stats",
           "fingerprint", "code_sig", "net_sig"]

_LOCK = threading.Lock()

# Flat counters, merged into profiler.dispatch_stats() (docs/capture.md).
_STATS = {
    "capture_steps": 0,           # captured trainer-step invocations
    "capture_hits": 0,            # signature-cache hits on captured execs
    "capture_misses": 0,          # first compile per signature
    "capture_retraces": 0,        # signature changes after first compile
    "capture_fallback_eager": 0,  # kill-switch / capture-failure eager runs
    "aot_cache_hits": 0,          # artifacts loaded from disk
    "aot_cache_misses": 0,        # artifacts absent: fresh trace + store
    "aot_cache_stale": 0,         # version/platform mismatch: recompiled
    "aot_cache_corrupt": 0,       # unreadable artifact: recompiled
    "aot_cache_writes": 0,        # artifacts written
    "aot_cache_evictions": 0,     # files removed by the size-cap GC
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


class CaptureError(RuntimeError):
    """Capture could not (re)build a step program (scalar-slot drift,
    unsupported trainer config). The caller falls back to eager."""


# ------------------------------------------------------------------ env knobs

def enabled():
    """Master kill switch: ``MXNET_TPU_CAPTURE=0`` makes :func:`capture`
    return an eager-fallback step (identical semantics, no compile)."""
    return os.environ.get("MXNET_TPU_CAPTURE", "1").strip().lower() \
        not in ("0", "false", "off")


def cache_dir():
    """AOT artifact directory (``MXNET_TPU_COMPILE_CACHE``), or None when
    persistence is disabled."""
    d = os.environ.get("MXNET_TPU_COMPILE_CACHE", "").strip()
    return d or None


def aot_enabled():
    return enabled() and cache_dir() is not None


def _integrity_enabled():
    """Is the in-graph step fingerprint armed (resilience.integrity)?
    Late import: capture loads before the resilience package in some
    entry orders."""
    from .resilience import integrity as _integrity

    return _integrity.fingerprint_enabled()


def _cache_limit_bytes():
    try:
        mb = float(os.environ.get("MXNET_TPU_COMPILE_CACHE_MAX_MB", "2048"))
    except ValueError:
        mb = 2048.0
    return int(mb * 1e6)


def _cache_salt():
    return os.environ.get("MXNET_TPU_COMPILE_CACHE_SALT", "")


def _schedule_token():
    """The kernel schedule-table identity folded into every AOT cache
    key (mxnet_tpu/tune/, docs/autotune.md): kernel builders resolve
    Pallas block sizes / int8 arrangements from the table at trace
    time, so a table change is a program change — a tuned program
    warm-loads fleet-wide, and a schedule edit can never false-hit an
    artifact compiled under the old schedule. '' when autotuning is
    disabled or the table is empty (both compile the default-schedule
    programs)."""
    try:
        from .tune import schedule as _tune_schedule

        return _tune_schedule.fingerprint_token()
    except Exception:
        return ""


# -------------------------------------------------------- retrace forensics

# Structured reasons for every captured-program recompile, newest last.
# Bounded; guarded by _LOCK (read by tests and crash-report consumers).
_RETRACE_LOG: list = []
_RETRACE_LOG_CAP = 64


def retrace_log():
    """Structured reasons for every captured-step recompile after its
    first build: ``{"label", "reason", "prev", "new", "t"}`` dicts,
    oldest first. The same reasons land in the dispatch ring (and so in
    watchdog crash reports) as ``capture_retrace:<label>:<reason>``."""
    with _LOCK:
        return [dict(e) for e in _RETRACE_LOG]


def clear_retrace_log():
    with _LOCK:
        del _RETRACE_LOG[:]


def _sig_reason(prev, new):
    """Human-readable diff of two capture signatures."""
    if prev is None:
        return "first capture"
    try:
        if len(prev) != len(new):
            return f"operand count changed {len(prev)} -> {len(new)}"
        for i, (p, n) in enumerate(zip(prev, new)):
            if p != n:
                return f"operand {i} changed {p} -> {n}"
    except TypeError:
        pass
    return f"signature changed {prev!r} -> {new!r}"


def _note_retrace(label, prev_sig, new_sig, reason=None):
    """Record one captured-program recompile: counter + structured log +
    dispatch-ring entry, so a watchdog crash report written later names
    the retrace cause instead of showing a silent compile stall."""
    reason = reason or _sig_reason(prev_sig, new_sig)
    _STATS["capture_retraces"] += 1
    entry = {"label": label, "reason": reason, "prev": repr(prev_sig),
             "new": repr(new_sig), "t": time.time()}
    with _LOCK:
        _RETRACE_LOG.append(entry)
        if len(_RETRACE_LOG) > _RETRACE_LOG_CAP:
            del _RETRACE_LOG[:-_RETRACE_LOG_CAP]
    _profiler.record_dispatch(f"capture_retrace:{label}:{reason}")
    _obs_flight.record("retrace", label=label, reason=reason)
    return entry


def note_recapture(label, prev, new, reason=None):
    """Public forensics entry for compile-site owners (the parallel
    ``ShardedTrainer``, serving): a program that must be REBUILT — mesh
    shrink, ``set_learning_rate``, elastic re-capture — records why,
    exactly like an in-place signature retrace."""
    return _note_retrace(label, prev, new, reason=reason)


# -------------------------------------------------------- fingerprinting

def fingerprint(parts):
    """THE shared key-schema digest for every capture/AOT compile site
    (gluon trainer steps, sharded step programs, serving buckets): a
    stable 32-hex hash of a structural-identity dict. One helper so a
    schema change (new field, version bump) cannot fork the cache-key
    format across sites."""
    return hashlib.sha256(json.dumps(
        parts, sort_keys=True, default=repr).encode()).hexdigest()[:32]


def code_sig(fn):
    """Structural signature of a callable's *computation*: its bytecode
    + consts, recursing into nested code objects (comprehensions, inner
    defs). Param shapes alone cannot distinguish ``relu`` from ``tanh``
    or one lambda loss body from another — without this in the program
    fingerprint a warm AOT cache would silently serve the wrong compiled
    program."""
    import types

    code = getattr(fn, "__code__", None)
    if code is None:  # callable object: sign its class's call path
        for name in ("hybrid_forward", "forward", "__call__"):
            meth = getattr(type(fn), name, None)
            code = getattr(meth, "__code__", None)
            if code is not None:
                break
    if code is None:
        return repr(fn)
    out = []
    stack = [code]
    while stack:
        c = stack.pop()
        out.append(c.co_code.hex())
        for const in c.co_consts:
            if isinstance(const, types.CodeType):
                stack.append(const)
            else:
                out.append(repr(const))
    return hashlib.sha256("|".join(out).encode()).hexdigest()[:16]


def net_sig(net):
    """Structural signature of a gluon block tree: the repr (layer
    types, activations, unit counts) + every distinct block class's
    forward bytecode, so architecture changes that keep the param
    shapes identical still change the program fingerprint."""
    parts = [repr(net)]
    seen = set()
    stack = [net]
    while stack:
        b = stack.pop()
        cls = type(b)
        key = f"{cls.__module__}.{cls.__qualname__}"
        if key not in seen:
            seen.add(key)
            fwd = getattr(b, "hybrid_forward", None) \
                or getattr(b, "forward", None)
            parts.append(f"{key}:{code_sig(fwd) if fwd else ''}")
        stack.extend(getattr(b, "_children", {}).values())
    return hashlib.sha256("|".join(sorted(parts)).encode()).hexdigest()[:16]


# ------------------------------------------------------- sanctioned compile

def _compile_jit(fn, jit_kwargs):
    """THE sanctioned ``jax.jit`` site for captured programs (graftlint
    TS002): every capture/AOT executable — trainer steps, elastic
    grad/apply programs, serving bucket forwards, deserialized AOT
    artifacts — compiles here, so donation/sharding conventions and the
    capture counters cannot be bypassed by a stray raw jit."""
    import jax

    return jax.jit(fn, **{k: v for k, v in jit_kwargs.items()
                          if v is not None})


# ----------------------------------------------------------- scalar sessions

_TLS = threading.local()


def _session():
    return getattr(_TLS, "session", None)


class _ScalarSession:
    """Dispatch-hook session threading dynamic scalar params through a
    captured program. Modes:

    - ``discover``: eager discovery pass — ops run normally; every
      dispatch of an op with declared ``dynamic_params`` records an
      operand slot (op name + keys + current values), fixing the slot
      order the compiled program consumes operands in.
    - ``record``: the jit trace — the same dispatches consume operand
      *tracers* (the program's trailing inputs) instead of baking the
      Python float of the moment into the executable.
    - ``replay``: per-step refresh — the update sweep's *Python* re-runs
      (schedules, bias corrections, ``num_update`` bookkeeping advance
      exactly as eagerly) while ops with ``mutate`` slots are skipped
      via identity outputs, collecting fresh operand values with no
      device work.
    """

    __slots__ = ("mode", "slots", "values", "operands", "pos", "off")

    def __init__(self, mode, slots=None, operands=None):
        self.mode = mode
        self.slots = slots if slots is not None else []
        self.values = []
        self.operands = operands
        self.pos = 0
        self.off = 0

    def __enter__(self):
        if getattr(_TLS, "session", None) is not None:
            raise CaptureError("nested capture sessions are not supported")
        _TLS.session = self
        _install_hook()
        return self

    def __exit__(self, *exc):
        _TLS.session = None
        return False

    # ---- dispatch hook body (see registry._CAPTURE_HOOK)
    def on_dispatch(self, op, params, arrays, is_traced):
        mode = self.mode
        dyn_keys, dyn_vals, static = op.split_dynamic(params)
        if mode == "record":
            if not dyn_keys or not is_traced:
                return NotImplemented
            if self.pos >= len(self.slots) or \
                    self.slots[self.pos] != (op.name, dyn_keys):
                raise CaptureError(
                    f"scalar slot drift at #{self.pos}: traced "
                    f"{(op.name, dyn_keys)}, discovered "
                    f"{self.slots[self.pos] if self.pos < len(self.slots) else None}")
            ops_in = self.operands[self.off:self.off + len(dyn_keys)]
            self.pos += 1
            self.off += len(dyn_keys)
            return op.closed(static)(*arrays, **dict(zip(dyn_keys, ops_in)))
        if dyn_keys:
            self.slots.append((op.name, dyn_keys))
            self.values.extend(dyn_vals)
        if mode == "discover":
            return NotImplemented  # run normally; slots now known
        # replay: skip the array math of mutating update ops — their
        # results are discarded; only the scalar Python above matters
        slots_m = op.mutate_slots(params)
        if not slots_m:
            return NotImplemented
        n_primary = op.n_out(params)
        prim = arrays[slots_m[0]]
        outs = tuple([prim] * n_primary) + tuple(arrays[s] for s in slots_m)
        return outs if len(outs) > 1 else outs[0]


def _capture_dispatch_hook(op, params, arrays, device, is_traced):
    sess = getattr(_TLS, "session", None)
    if sess is None:
        return NotImplemented
    return sess.on_dispatch(op, params, arrays, is_traced)


_HOOK_INSTALLED = False


def _install_hook():
    global _HOOK_INSTALLED
    with _LOCK:
        if _HOOK_INSTALLED:
            return
        from .ops import registry

        registry._set_capture_hook(_capture_dispatch_hook)
        _HOOK_INSTALLED = True


# ------------------------------------------------------------ AOT artifacts

_MAGIC = b"MXTPUAOT1\n"


def _backend_sig():
    import jax

    devs = jax.devices()
    return f"{devs[0].platform}:{len(devs)}"


def _versions():
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


class CompileCache:
    """On-disk store of compiled-program artifacts.

    Layout under the root: ``programs/<key>.aotx`` — a header (schema,
    jax/jaxlib versions, backend, payload SHA-256) followed by the
    ``jax.export`` serialization of the traced program — and ``xla/``,
    jax's persistent compilation cache of XLA *executables*, enabled for
    the process when this cache is. A warm load therefore skips both
    Python tracing/lowering (our artifact) and XLA compilation (jax's).

    Invalidation (docs/capture.md): the key hashes the caller's
    structural fingerprint + avals/sharding/donation signature + backend
    topology + ``MXNET_TPU_COMPILE_CACHE_SALT``; the header carries the
    jax/jaxlib versions, so a version bump is detected as *stale* and
    recompiled in place. Corrupt artifacts (bad magic, truncated, hash
    mismatch, undeserializable) are treated identically — fresh compile,
    never a crash.
    """

    def __init__(self, root):
        self.root = root
        self.programs = os.path.join(root, "programs")
        self.xla = os.path.join(root, "xla")
        os.makedirs(self.programs, exist_ok=True)
        os.makedirs(self.xla, exist_ok=True)

    def xla_subcache(self):
        """Context manager pointing jax's persistent compilation cache at
        ``<root>/xla`` for the duration of one capture/AOT compile, so
        the XLA-executable layer persists too — WITHOUT leaving a
        zero-threshold global cache armed for every unrelated jit in the
        process. An operator-configured cache dir is left alone. The
        sticky "cache checked" latch is reset on both transitions so the
        scoped enable works mid-process."""
        import contextlib

        import jax

        @contextlib.contextmanager
        def scoped():
            try:
                # everything fallible (private-API import included) is
                # probed BEFORE the first config.update, so an
                # unsupported jax can never strand a partially-applied
                # zero-threshold cache config on the whole process
                prior_dir = jax.config.jax_compilation_cache_dir
                if prior_dir:
                    yield  # operator-configured: leave it alone
                    return
                prior = {
                    "jax_compilation_cache_dir": prior_dir,
                    "jax_persistent_cache_min_compile_time_secs":
                        jax.config.jax_persistent_cache_min_compile_time_secs,
                    "jax_persistent_cache_min_entry_size_bytes":
                        jax.config.jax_persistent_cache_min_entry_size_bytes,
                }
                from jax._src import compilation_cache as _cc
            except Exception:  # XLA layer unsupported: program layer only
                yield
                return
            try:
                jax.config.update("jax_compilation_cache_dir", self.xla)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0)
                _cc.reset_cache()
            except Exception:
                for k, v in prior.items():  # roll back a partial apply
                    try:
                        jax.config.update(k, v)
                    except Exception:
                        pass
                yield
                return
            try:
                yield
            finally:
                try:
                    for k, v in prior.items():
                        jax.config.update(k, v)
                    _cc.reset_cache()
                except Exception:
                    pass

        return scoped()

    # ------------------------------------------------------------------ keys
    def key(self, label, fingerprint, sig):
        blob = json.dumps({
            "label": label, "fingerprint": fingerprint, "sig": repr(sig),
            "backend": _backend_sig(), "salt": _cache_salt(),
            "schedule": _schedule_token(),
        }, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:40]

    def _path(self, key):
        return os.path.join(self.programs, f"{key}.aotx")

    # ------------------------------------------------------------------- load
    def load(self, key):
        """Deserialize the artifact under ``key``; None on miss/stale/
        corrupt (counting each), never an exception."""
        path = self._path(key)
        if not os.path.isfile(path):
            _STATS["aot_cache_misses"] += 1  # absent: fresh trace+store
            return None
        try:
            with open(path, "rb") as f:
                blob = f.read()
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            off = len(_MAGIC)
            hlen = int.from_bytes(blob[off:off + 4], "big")
            header = json.loads(blob[off + 4:off + 4 + hlen])
            payload = blob[off + 4 + hlen:]
        except Exception:
            _STATS["aot_cache_corrupt"] += 1
            return None
        vers = _versions()
        if header.get("jax") != vers["jax"] \
                or header.get("jaxlib") != vers["jaxlib"] \
                or header.get("backend") != _backend_sig():
            _STATS["aot_cache_stale"] += 1
            try:  # never serveable again under this key: free it now
                os.remove(path)
            except OSError:
                pass
            return None
        if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
            _STATS["aot_cache_corrupt"] += 1
            return None
        try:
            from jax import export as _export

            exported = _export.deserialize(bytearray(payload))
        except Exception:
            _STATS["aot_cache_corrupt"] += 1
            return None
        try:  # freshen mtime so the size-cap GC evicts cold artifacts,
            os.utime(path)  # not the most-reloaded ones
        except OSError:
            pass
        return exported

    # ------------------------------------------------------------------ store
    def store(self, key, exported, label=""):
        """Atomically persist one exported program; best-effort (a full
        disk must never fail the compile that produced the program)."""
        try:
            payload = bytes(exported.serialize())
            header = dict(_versions())
            header.update({
                "schema": 1, "backend": _backend_sig(), "label": label,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "created": time.time(),
            })
            hbytes = json.dumps(header, sort_keys=True).encode()
            path = self._path(key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(len(hbytes).to_bytes(4, "big"))
                f.write(hbytes)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _STATS["aot_cache_writes"] += 1
            self.gc()
            return path
        except Exception:
            return None

    # --------------------------------------------------------------------- gc
    def gc(self, limit_bytes=None):
        """Size-cap eviction: while the cache exceeds
        ``MXNET_TPU_COMPILE_CACHE_MAX_MB``, delete the oldest-mtime
        files (program artifacts and XLA-cache entries alike)."""
        limit = _cache_limit_bytes() if limit_bytes is None else limit_bytes
        entries = []
        total = 0
        for d in (self.programs, self.xla):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                p = os.path.join(d, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
        if total <= limit:
            return 0
        evicted = 0
        for _, size, p in sorted(entries):
            if total <= limit:
                break
            try:
                os.remove(p)
            except OSError:
                continue
            total -= size
            evicted += 1
            _STATS["aot_cache_evictions"] += 1
        return evicted


_CACHES: dict = {}


def compile_cache():
    """The process CompileCache for ``MXNET_TPU_COMPILE_CACHE``, or None
    when persistence is off (read per call: tests flip the env var)."""
    d = cache_dir()
    if d is None:
        return None
    with _LOCK:
        cache = _CACHES.get(d)
        if cache is None:
            try:
                cache = CompileCache(d)
            except OSError:
                return None
            _CACHES[d] = cache
    return cache


def _precompile(jitted, example_args):
    """Force trace + XLA compile now (build time), so first-step latency
    never lands inside an armed watchdog guard. Falls back to the lazy
    jitted callable for programs AOT lowering can't specialize."""
    try:
        return jitted.lower(*example_args).compile()
    except Exception:
        return jitted


def aot_compile(fn, *, label, fingerprint, example_args, sig=None,
                in_shardings=None, out_shardings=None, donate_argnums=()):
    """Compile ``fn`` through the sanctioned site, persisting/loading the
    traced program via the AOT cache when enabled.

    Warm path: deserialize the artifact (skips Python tracing and
    lowering) and compile its ``call`` — which the persistent XLA
    subcache resolves to a stored executable (skips XLA compilation).
    Cold path: jit ``fn``, export with ``example_args``, store. Both
    paths execute the exported program form when a cache is configured,
    so cold and warm runs are bitwise-identical by construction.
    """
    jit_kwargs = {"in_shardings": in_shardings,
                  "out_shardings": out_shardings,
                  "donate_argnums": donate_argnums or None}
    t0 = time.perf_counter()
    perf_fp = _perf_identity(fingerprint, example_args, sig)

    def _ledger(compiled, aot_hit=False):
        # static perf attribution (observability.perf): every compile
        # through this site — captured steps, sharded programs, serving
        # buckets — lands one ledger entry (cost/memory analysis + wall
        # compile time) under the SAME (fingerprint, signature)
        # identity that keys the AOT artifact, so the perf gate and the
        # compile cache agree on identity by construction and two
        # programs can never merge into one entry
        _obs_perf.note_compile(label, perf_fp, compiled,
                               time.perf_counter() - t0, aot_hit=aot_hit)
        return compiled

    cache = compile_cache()
    if cache is None or not enabled():
        return _ledger(_precompile(_compile_jit(fn, jit_kwargs),
                                   example_args))
    key = cache.key(label, fingerprint, sig if sig is not None
                    else _avals_sig(example_args))
    # load() counts the outcome: absent -> misses, version/backend
    # mismatch -> stale, unreadable -> corrupt (each a distinct series,
    # so cold-cache misses never masquerade as invalidation churn)
    exported = cache.load(key)
    aot_hit = exported is not None
    if exported is None:
        jitted = _compile_jit(fn, jit_kwargs)
        try:
            from jax import export as _export

            exported = _export.export(jitted)(*example_args)
            cache.store(key, exported, label=label)
        except Exception:
            # program not exportable (callbacks, unsupported primitive):
            # serve the plain executable; persistence is best-effort
            with cache.xla_subcache():
                return _ledger(_precompile(jitted, example_args))
    else:
        _STATS["aot_cache_hits"] += 1
    wrapped = _compile_jit(exported.call,
                           {"donate_argnums": donate_argnums or None})
    with cache.xla_subcache():
        return _ledger(_precompile(wrapped, example_args), aot_hit=aot_hit)


def _avals_sig(args):
    """Flat (shape, dtype, sharding) signature of a pytree of arrays."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sh = getattr(leaf, "sharding", None)
        out.append((shape, dtype, repr(sh) if sh is not None else None))
    return tuple(out)


def _perf_identity(fingerprint, example_args, sig=None):
    """The perf-ledger identity of one compiled program: the caller's
    structural fingerprint folded with its aval signature — exactly the
    pair the AOT cache key hashes. Execution sites recompute this from
    the same inputs so their timings land on the entry their compile
    created."""
    full_sig = sig if sig is not None else _avals_sig(example_args)
    return _obs_perf.combined_fingerprint(fingerprint, repr(full_sig))


# ------------------------------------------------------------- CapturedExec

class CapturedExec:
    """A keyed captured executable: per-signature compile cache with
    retrace forensics and AOT persistence.

    The compile path for ``parallel.ShardedTrainer`` fused/elastic steps
    and serving ``Predictor`` bucket forwards. ``sig_argnums`` selects
    which positional args key the per-call signature (the batch operands;
    state avals are fixed per instance and belong in ``fingerprint``), so
    the steady-state hot path costs one small tuple build + dict hit.
    """

    def __init__(self, fn, *, label, fingerprint="", in_shardings=None,
                 out_shardings=None, donate_argnums=(), sig_argnums=()):
        self._fn = fn
        self.label = label
        self.fingerprint = fingerprint
        self._in_shardings = in_shardings
        self._out_shardings = out_shardings
        self._donate = tuple(donate_argnums or ())
        self._sig_argnums = tuple(sig_argnums)
        self._entries = {}
        self._entry_fps = {}  # sig -> perf-ledger identity (fp ⊕ avals)
        self._last_sig = None
        self._lock = threading.Lock()

    def _sig_of(self, args):
        return tuple((tuple(args[i].shape), str(args[i].dtype))
                     for i in self._sig_argnums)

    def __call__(self, *args):
        sig = self._sig_of(args)
        entry = self._entries.get(sig)
        if entry is None:
            with self._lock:
                entry = self._entries.get(sig)
                if entry is None:
                    if self._last_sig is not None or self._entries:
                        _note_retrace(self.label, self._last_sig, sig)
                    _STATS["capture_misses"] += 1
                    avals = _avals_sig(args)
                    entry = aot_compile(
                        self._fn, label=self.label,
                        fingerprint=self.fingerprint,
                        example_args=args, sig=avals,
                        in_shardings=self._in_shardings,
                        out_shardings=self._out_shardings,
                        donate_argnums=self._donate)
                    self._entry_fps[sig] = _perf_identity(
                        self.fingerprint, args, avals)
                    self._entries[sig] = entry
                    self._last_sig = sig
        else:
            _STATS["capture_hits"] += 1
        # dynamic perf attribution: with MXNET_TPU_OBS_DEVICE_TIME on,
        # every call blocks on its outputs (dependency-chained timing,
        # PERF.md) and feeds THIS signature's ledger entry (the same
        # fp ⊕ avals identity its compile registered); off, this is one
        # global check around a plain call
        return _obs_perf.timed_call(entry, args, self.label,
                                    self._entry_fps[sig])

    @property
    def compiled_signatures(self):
        return sorted(self._entries)


# ------------------------------------------------- gluon Trainer capture

def _absorb_session(outer, inner):
    """Merge a nested TraceSession's reads/mutations into ``outer`` —
    used when the captured step wraps its update sweep in its own
    session (to learn pre/post values for the sentinel select) while the
    enclosing discovery/trace session still needs every state cell."""
    if outer is None:
        return
    for nd_ in inner.captured:
        if id(nd_) in outer.created:
            continue
        outer.orig.setdefault(id(nd_), inner.orig[id(nd_)])
        if id(nd_) not in outer._captured_ids:
            outer._captured_ids.add(id(nd_))
            outer.captured.append(nd_)
    for nd_ in inner.mutated:
        if id(nd_) in outer.created:
            continue
        if id(nd_) not in outer._mutated_ids:
            outer._mutated_ids.add(id(nd_))
            outer.mutated.append(nd_)


class CapturedTrainerStep:
    """One gluon training step — forward, backward, gradient allreduce,
    optimizer sweep, sentinel finite-check — as a single donated XLA
    executable with dynamic scalar operands.

    Bitwise-equal to the eager path (eager fwd/bwd + ``Trainer.step``
    with or without ``engine.bulk``-ed updates), including optimizers
    whose per-step scalars drift (Adam bias correction, lr schedules):
    those enter as runtime operands refreshed by a per-step scalar
    replay, not baked constants (docs/capture.md).

    Parameters
    ----------
    net : initialized gluon Block
    loss_fn : callable(pred_nd, label_nd) -> NDArray (head grad = ones,
        exactly like calling ``loss.backward()`` eagerly)
    trainer : gluon.Trainer (``update_on_kvstore`` unsupported)
    batch_size : rescale denominator for ``Trainer.step``; default = the
        batch's row count
    sentinel : HealthSentinel; default = the one attached to ``trainer``
        (which is bypassed on the captured path — the check runs fused,
        the policy applies on the host from the returned flag)
    loss_scaler : amp.LossScaler — its scale becomes a runtime operand:
        the loss is scaled before backward, gradients unscale before the
        finite check and update, and the scaler's host schedule advances
        from the program's overflow flag (``note_finite``, so
        ``has_overflow`` never host-syncs under capture).
    numerics : observability.numerics.NumericsTap — in-graph numerics
        telemetry: per-layer/per-param stats computed on-device as one
        extra side output, with sampling cadence and stat selection as
        runtime operands (docs/observability.md "Numerics telemetry").
        Default: armed from ``MXNET_TPU_NUMERICS``; None keeps the
        program identical to the pre-telemetry build.
    """

    def __init__(self, net, loss_fn, trainer, batch_size=None,
                 sentinel=None, loss_scaler=None, numerics=None,
                 label="trainer_step"):
        self.net = net
        self.loss_fn = loss_fn
        self.trainer = trainer
        self.label = label
        self._batch_size = batch_size
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if trainer._update_on_kvstore:
            raise CaptureError(
                "capture() does not support update_on_kvstore trainers "
                "(the update runs outside the step program)")
        self.sentinel = sentinel if sentinel is not None \
            else getattr(trainer, "_sentinel", None)
        self.loss_scaler = loss_scaler
        self.numerics = numerics if numerics is not None \
            else _obs_numerics.default_tap()
        if self.numerics is not None:
            self.numerics.bind(net, trainer)
        self._entries = {}
        self._last_sig = None
        self._step_count = 0
        # last step's in-graph fingerprint output (resilience.integrity;
        # lazy — host-read only on last_fingerprint access)
        self._last_fp_out = None

    @property
    def last_fingerprint(self):
        """uint32 fingerprint of the last executed step, or None when
        fingerprinting is off (resilience.integrity). Identical across
        the captured, eager-fallback, and bulk paths by construction."""
        if self._last_fp_out is None:
            return None
        import numpy as np

        return int(np.asarray(self._last_fp_out))

    def _note_eager_fp(self):
        """Host-side fingerprint of the step that just ran eagerly (the
        kill-switch / capture-failure path) — folds the same operand set
        as the in-graph output, so eager and captured agree bitwise."""
        from .resilience import integrity as _integrity

        if not _integrity.fingerprint_enabled():
            self._last_fp_out = None
            return
        import numpy as np

        named_p, named_g = _integrity.net_named_state(self.net)
        self._last_fp_out = np.uint32(
            _integrity.step_fold_host(named_p, named_g))
        _integrity.note_fingerprint_step()

    # ------------------------------------------------------------ step python
    def _opt_host_snapshot(self):
        opt = self.trainer._optimizer
        return (opt.num_update, dict(opt._index_update_count),
                opt.rescale_grad)

    def _opt_host_restore(self, snap):
        opt = self.trainer._optimizer
        opt.num_update, count, opt.rescale_grad = snap
        opt._index_update_count = dict(count)

    def _grad_list(self):
        out = []
        for p in self.trainer._params:
            if p.grad_req != "null":
                out.extend(p.list_grad())
        return out

    def _health_flags(self, grads):
        """Fused health check over the gradients, as traced values:
        ``(finite, norm_ok_or_None)`` — ``multi_all_finite`` plus the
        grad-norm bound when the sentinel sets one, mirroring
        ``HealthSentinel._grads_healthy`` (two separate flags so the
        host attributes a trip to the same counter eager would:
        ``sentinel_nonfinite`` vs ``sentinel_grad_norm_trips``)."""
        from .ndarray import ndarray as _nd

        finite = _nd.imperative_invoke(
            "multi_all_finite", *grads, num_arrays=len(grads))[0]
        flag = finite.data_.reshape(())
        thr = (self.sentinel.grad_norm_threshold
               if self.sentinel is not None else None)
        if thr is None:
            return flag, None
        import jax.numpy as jnp

        sq = _nd.imperative_invoke(
            "multi_sum_sq", *grads, num_arrays=len(grads))
        total = sum(s.data_.reshape(()) for s in sq)
        # same comparison shape as eager (norm vs threshold, not the
        # squared form) so threshold-boundary rounding agrees
        norm_ok = jnp.sqrt(total) <= jnp.float32(thr)
        return flag, norm_ok

    def _run_step_python(self, x_nd, y_nd, batch_size, scale_val=None,
                         check_gate=None, tap_ops=None):
        """The step body re-run by discovery and by the jit trace. The
        update sweep runs in a nested TraceSession so the sentinel
        select knows each cell's pre-update value. ``check_gate`` is the
        sentinel's cadence operand (1.0 = this step is a check step):
        on off-cadence steps the eager ``before_update`` never looks at
        the gradients, so the select must let even an unhealthy batch
        through — except the loss-scaler's finiteness gate, which eager
        AMP applies every step. ``tap_ops`` is the numerics tap's
        column-selection-mask operand and marks the SAMPLED program
        variant: when present, the per-layer stats matrix computes and
        rides out as one extra side output; when None with a tap armed,
        this body builds the base (off-cadence) variant — no hooks, no
        stats, only the finite gate for halt/skip policies."""
        import jax.numpy as jnp

        from . import autograd
        from .jit import TraceSession, _active
        from .ndarray.ndarray import NDArray
        from .resilience import integrity as _integrity

        trainer = self.trainer
        tap = self.numerics
        # "full" = the sampled-step program variant (stats side output);
        # with tap_ops=None and a tap armed this body builds the BASE
        # variant: for a record-policy tap literally the untapped
        # program, for halt/skip the untapped program + the fused
        # finite flag and its weight-write select (the protection that
        # must run every step regardless of sampling)
        full = tap is not None and tap_ops is not None
        hooks = acts = None
        if full:
            hooks, acts = tap.install_hooks(self.net)
        try:
            with autograd.record():
                out = self.net(x_nd)
                loss = self.loss_fn(out, y_nd)
                if scale_val is not None:
                    scale_nd = NDArray(jnp.asarray(scale_val, jnp.float32))
                    sess = _active()
                    if sess is not None:
                        sess.note_created(scale_nd)
                    loss_b = loss * scale_nd
                else:
                    loss_b = loss
        finally:
            if full:
                tap.remove_hooks(hooks)
        loss_b.backward()
        grads = self._grad_list()
        if scale_val is not None:
            inv = 1.0 / scale_nd
            for g in grads:
                g._set_data((g * inv)._data)
        # a record-policy tap adds NO per-step device work: its finite
        # signal rides the sampled stats matrix's nonfinite column, so
        # the fused every-step finite reduction is only built when
        # something gates on it (sentinel, AMP scaler, halt/skip tap)
        flags = self._health_flags(grads) if (
            self.sentinel is not None or scale_val is not None
            or (tap is not None and tap.gates_updates)) else None
        tap_params = tap_pre = None
        if full:
            tap_params = tap.tapped_params(trainer)
            tap_pre = [p.data()._data for p in tap_params]
        outer = _active()
        trainer._optimizer.rescale_grad = trainer._scale / batch_size
        with TraceSession() as upd:
            trainer._allreduce_grads()
            trainer._update()
        _absorb_session(outer, upd)
        tap_out = None
        if full:
            # stats see the RAW computed update (post - pre), before the
            # health select below decides whether it lands
            named_grads = []
            for p in tap_params:
                for g in p.list_grad():
                    named_grads.append((p.name, g.data_))
            named_pre = [(p.name, d) for p, d in zip(tap_params, tap_pre)]
            named_post = [(p.name, p.data()._data) for p in tap_params]
            tap_out = tap.graph_stats(named_grads, named_pre, named_post,
                                      acts, tap_ops)
        if flags is not None:
            finite, norm_ok = flags
            ok = finite if norm_ok is None \
                else jnp.logical_and(finite, norm_ok)
            passed = None
            if self.sentinel is not None or scale_val is not None:
                if check_gate is not None:
                    passed = jnp.logical_or(ok, check_gate == 0)
                    if scale_val is not None:
                        # AMP overflow skips are never sampled
                        passed = jnp.logical_and(passed, finite)
                else:
                    passed = ok
            if tap is not None and tap.gates_updates:
                # halt/skip numerics policies: a non-finite batch never
                # touches the weights, sampled or not (the AMP rule); a
                # record-only tap leaves the program bitwise-transparent
                passed = finite if passed is None \
                    else jnp.logical_and(passed, finite)
            if passed is not None:
                for cell in upd.mutated:
                    cell._data = jnp.where(passed, cell._data,
                                           upd.orig[id(cell)])
        # in-graph step fingerprint (resilience.integrity): folded AFTER
        # the sentinel select so it digests the values that actually
        # landed — rides out as one extra scalar of the SAME program
        fp = None
        if _integrity.fingerprint_enabled():
            fp = _integrity.step_fold(*_integrity.net_named_state(self.net))
        return loss, flags, tap_out, fp

    # ------------------------------------------------------------------ build
    def _build(self, x_nd, y_nd, batch_size, sig):
        """Discovery + capture + compile, with the XLA subcache scoped
        around the WHOLE build when persistence is on: the discovery
        pass's per-op eager executables then also resolve from the
        persistent cache, so a warm cold-start skips those compiles too,
        not just the whole-program one."""
        import contextlib

        cache = compile_cache()
        scope = cache.xla_subcache() if cache is not None \
            else contextlib.nullcontext()
        with scope:
            return self._build_inner(x_nd, y_nd, batch_size, sig)

    def _build_inner(self, x_nd, y_nd, batch_size, sig):
        import jax.numpy as jnp

        from .jit import TraceSession
        from .ndarray.ndarray import NDArray

        import numpy as np

        host_snap = self._opt_host_snapshot()
        scale0 = (self.loss_scaler.loss_scale
                  if self.loss_scaler is not None else None)
        has_gate = self.sentinel is not None
        has_tap = self.numerics is not None
        tap0 = self.numerics.sel_values() if has_tap else None
        with _ScalarSession("discover") as scal, TraceSession() as sess:
            sess.note_created(x_nd)
            sess.note_created(y_nd)
            try:
                self._run_step_python(x_nd, y_nd, batch_size, scale0,
                                      1.0 if has_gate else None, tap0)
            finally:
                for m in sess.mutated:
                    m._data = sess.orig[id(m)]
                self._opt_host_restore(host_snap)
        slots = list(scal.slots)
        n_dyn = len(scal.values)
        state_cells = list(sess.captured)
        has_flag = self.sentinel is not None \
            or self.loss_scaler is not None \
            or (has_tap and self.numerics.gates_updates)
        has_scale = self.loss_scaler is not None
        has_norm = self.sentinel is not None \
            and self.sentinel.grad_norm_threshold is not None
        from .resilience import integrity as _integrity

        has_fp = _integrity.fingerprint_enabled()
        tap_rows = self.numerics.rows if has_tap else ()
        step = self

        def make_pure(with_tap):
            """One program variant: ``with_tap`` is the SAMPLED-step
            form (stats side output + one trailing mask operand); the
            base form is the off-cadence hot path — identical to the
            pre-telemetry program for a record-policy tap, plus only
            the fused finite gate for halt/skip policies."""

            def pure(arg_datas, state_datas, dyn_vals):
                saved = [c._data for c in state_cells]
                snap = step._opt_host_snapshot()
                try:
                    for c, d in zip(state_cells, state_datas):
                        c._data = d
                    x2, y2 = NDArray(arg_datas[0]), NDArray(arg_datas[1])
                    idx = n_dyn
                    scale_t = dyn_vals[idx] if has_scale else None
                    idx += int(has_scale)
                    gate_t = dyn_vals[idx] if has_gate else None
                    idx += int(has_gate)
                    tap_t = dyn_vals[idx] if with_tap else None
                    with _ScalarSession("record", slots, dyn_vals), \
                            TraceSession() as inner:
                        inner.note_created(x2)
                        inner.note_created(y2)
                        loss, flags, tap_out, fp = step._run_step_python(
                            x2, y2, batch_size, scale_t, gate_t, tap_t)
                    if with_tap and \
                            tuple(step.numerics.rows) != tuple(tap_rows):
                        raise CaptureError(
                            "numerics tap row plan drifted between "
                            f"discovery and trace ({len(tap_rows)} -> "
                            f"{len(step.numerics.rows)} rows); recapture "
                            "with a fresh CapturedTrainerStep")
                    outs = [loss.data_]
                    if flags is not None:
                        outs.append(flags[0])
                        if flags[1] is not None:
                            outs.append(flags[1])
                    if fp is not None:
                        outs.append(fp)
                    if tap_out is not None:
                        outs.append(tap_out)
                    new_state = [c._data for c in state_cells]
                finally:
                    for c, d in zip(state_cells, saved):
                        c._data = d
                    step._opt_host_restore(snap)
                return outs, new_state

            return pure

        fingerprint = self._fingerprint(sig, slots, state_cells)
        # numpy f32 scalars: the per-step refresh passes np.float32 too,
        # so the example avals match the steady-state call exactly (a
        # Python float would trace a weak-typed operand and the compiled
        # program would reject the refreshed values)
        base_dyn = ([np.float32(v) for v in scal.values]
                    + ([np.float32(scale0)] if has_scale else [])
                    + ([np.float32(1.0)] if has_gate else []))
        example = ([x_nd.data_, y_nd.data_],
                   [c._data for c in state_cells], list(base_dyn))
        fn = aot_compile(make_pure(False), label=self.label,
                         fingerprint=fingerprint, example_args=example,
                         donate_argnums=(1,))
        fn_tap = None
        fp_tap = None
        if has_tap:
            # the sampled-step variant is its own program (extra output
            # + trailing mask operand) under a variant-tagged identity;
            # cadence picks between the two PREBUILT executables, so an
            # interval change can never retrace
            fingerprint_tap = self._fingerprint(sig, slots, state_cells,
                                                variant="tap_sample")
            example_tap = ([x_nd.data_, y_nd.data_],
                           [c._data for c in state_cells],
                           list(base_dyn) + [self.numerics.sel_values()])
            fn_tap = aot_compile(make_pure(True),
                                 label=f"{self.label}:tap_sample",
                                 fingerprint=fingerprint_tap,
                                 example_args=example_tap,
                                 donate_argnums=(1,))
            fp_tap = _perf_identity(fingerprint_tap, example_tap)
        entry = {
            "fn": fn, "fn_tap": fn_tap, "cells": state_cells,
            "slots": slots,
            "has_flag": has_flag, "has_scale": has_scale,
            "has_gate": has_gate, "has_norm": has_norm,
            "has_tap": has_tap, "tap_rows": tap_rows,
            "tap_gates": has_tap and self.numerics.gates_updates,
            "has_fp": has_fp,
            "fp_idx": 1 + int(has_flag) + int(has_norm),
            "tap_idx": 1 + int(has_flag) + int(has_norm) + int(has_fp),
            "states_ref": self.trainer._updaters[0].states,
            "ctx": x_nd.context,
            # the same fp ⊕ avals identity aot_compile just ledgered,
            # so the per-step device timings land on this program's entry
            "fingerprint": _perf_identity(fingerprint, example),
            "fingerprint_tap": fp_tap,
        }
        self._entries[sig] = entry
        self._last_sig = sig
        return entry

    def _fingerprint(self, sig, slots, state_cells, variant=None):
        trainer = self.trainer
        opt = trainer._optimizer
        parts = {
            # base vs tap_sample program variant of one captured step
            "variant": variant,
            "net": [(n, tuple(c.shape), str(c.dtype))
                    for n, c in sorted(
                        self.net._collect_params_with_prefix().items())],
            # param avals can't distinguish relu from tanh or one lambda
            # loss from another — the computation structure must key too
            "net_struct": net_sig(self.net),
            "loss_code": code_sig(self.loss_fn),
            "optimizer": type(opt).__name__,
            "loss": getattr(self.loss_fn, "__qualname__",
                            type(self.loss_fn).__name__),
            "sig": repr(sig),
            "slots": repr(slots),
            "n_state": len(state_cells),
            "sentinel": None if self.sentinel is None else
                (self.sentinel.policy, self.sentinel.grad_norm_threshold),
            "scaler": self.loss_scaler is not None,
            # row plan + column schema + gating semantics; cadence and
            # stat selection are runtime operands and must NOT key here
            "numerics": None if self.numerics is None
                else self.numerics.plan_signature(),
            # the in-graph step fingerprint adds an output to the traced
            # program (resilience.integrity) — an AOT artifact compiled
            # with the other setting must never false-hit
            "integrity": _integrity_enabled(),
        }
        return fingerprint(parts)

    # ------------------------------------------------------------------- call
    def _sig_of(self, x_nd, y_nd, batch_size):
        return ((tuple(x_nd.shape), str(x_nd.data_.dtype)),
                (tuple(y_nd.shape), str(y_nd.data_.dtype)),
                float(batch_size))

    def _entry_valid(self, entry):
        """A checkpoint restore (``set_states_bytes``) rebinds the
        updater's state dict to fresh cells; the captured program must
        then re-discover its state list instead of silently reading the
        orphaned ones."""
        return entry["states_ref"] is self.trainer._updaters[0].states

    def __call__(self, x, y, batch_size=None):
        import numpy as np

        from .ndarray.ndarray import NDArray
        from .resilience import faults as _faults
        from .resilience import watchdog as _watchdog

        _STATS["capture_steps"] += 1
        x_nd = x if isinstance(x, NDArray) else NDArray(x)
        y_nd = y if isinstance(y, NDArray) else NDArray(y)
        if not enabled():
            _STATS["capture_fallback_eager"] += 1
            return self._eager_step(x_nd, y_nd, batch_size)
        # the nan_grad drill: a captured program cannot be poisoned from
        # the outside per-step, so the fault poisons the batch instead —
        # NaN flows through the real compiled fwd/bwd into the fused
        # sentinel check, same detection surface as the eager hook
        if _faults.active("nan_grad"):
            f = _faults.get("nan_grad")
            if f is not None and f.should_fire():
                x_nd = NDArray(x_nd.data_ * np.float32("nan"), x_nd.context)
        # the nonfinite_grad drill's captured form: poison the TARGET
        # layer's weight so the NaN flows through the real compiled
        # fwd/bwd into that layer's activations and gradients — the
        # detection surface (fused finite flag + per-layer tap rows)
        # and the bisect tool then localize it, never the injection
        _faults.maybe_nonfinite_grad(self.trainer._params, where="param")
        bs = batch_size if batch_size is not None else (
            self._batch_size if self._batch_size is not None
            else int(x_nd.shape[0]))
        sig = self._sig_of(x_nd, y_nd, bs)
        entry = self._entries.get(sig)
        if entry is not None and not self._entry_valid(entry):
            _note_retrace(self.label, sig, sig,
                          reason="trainer state rebound "
                                 "(checkpoint restore)")
            del self._entries[sig]
            entry = None
        if entry is None:
            if self._last_sig is not None and self._last_sig != sig:
                _note_retrace(self.label, self._last_sig, sig)
            _STATS["capture_misses"] += 1
            try:
                entry = self._build(x_nd, y_nd, bs, sig)
            except CaptureError:
                _STATS["capture_fallback_eager"] += 1
                return self._eager_step(x_nd, y_nd, batch_size)
        else:
            _STATS["capture_hits"] += 1
        # scalar replay: re-run the update sweep's Python (schedules,
        # bias corrections, num_update) with array math skipped, giving
        # this step's fresh operand values. Snapshot the host bookkeeping
        # first: a batch the fused health check rejects never reaches the
        # update sweep on the eager path, so its replay must un-advance
        # (Adam's t, num_update) to stay bitwise with eager skip_batch.
        host_snap = self._opt_host_snapshot()
        self.trainer._optimizer.rescale_grad = \
            self.trainer._scale / bs
        with _ScalarSession("replay") as rep:
            self.trainer._update()
        if [s for s in rep.slots] != entry["slots"]:
            self._opt_host_restore(host_snap)  # undo the replay advance
            raise CaptureError(
                f"scalar replay diverged from the captured program "
                f"(captured {len(entry['slots'])} slots, replayed "
                f"{len(rep.slots)}); recapture with a fresh "
                "CapturedTrainerStep")
        dyn = [np.float32(v) for v in rep.values]
        if entry["has_scale"]:
            dyn.append(np.float32(self.loss_scaler.loss_scale))
        # sentinel cadence (HealthSentinel.check_every): same counter
        # and sampling rule as the eager before_update — an off-cadence
        # step's gate operand disables the in-program select, so even an
        # unhealthy batch updates the weights, exactly like eager
        checking = False
        if self.sentinel is not None:
            self.sentinel._step += 1
            checking = (self.sentinel._step - 1) \
                % self.sentinel.check_every == 0
        if entry["has_gate"]:
            dyn.append(np.float32(1.0 if checking else 0.0))
        tap_sampled = False
        if entry["has_tap"]:
            # the cadence picks between the two PREBUILT program
            # variants and the column selection is a trailing operand
            # of the sampled one: changing either at runtime never
            # retraces (tested by the compile-count probe)
            tap_sampled = self.numerics.tick()
            if tap_sampled:
                dyn.append(self.numerics.sel_values())
        self._step_count += 1
        _watchdog.note_step(self._step_count)
        try:
            # numerics_sampled marks the tap's cadence steps: they pay
            # the stats variant + host pull by design, so the step-time
            # drift detector excludes them (a configured sampling
            # cadence is not an anomaly)
            span_attrs = {"step": self._step_count}
            if tap_sampled:
                span_attrs["numerics_sampled"] = True
            with _obs_trace.span("train.captured_step", **span_attrs), \
                    _watchdog.guard("step",
                                    detail="capture.CapturedTrainerStep",
                                    step=self._step_count):
                _faults.maybe_hang("hang_step")
                with _obs_trace.span("captured.execute"):
                    outs, new_state = _obs_perf.timed_call(
                        entry["fn_tap"] if tap_sampled else entry["fn"],
                        ([x_nd.data_, y_nd.data_],
                         [c._data for c in entry["cells"]], dyn),
                        self.label,
                        entry["fingerprint_tap"] if tap_sampled
                        else entry["fingerprint"])
        except _watchdog.StallError as e:
            if not self._stall_rollback(e):
                # the stalled step never applied: un-advance the replay's
                # host bookkeeping (Adam's t, num_update) so a caller that
                # catches the stall and keeps training stays bitwise with
                # eager (a successful rollback restores it from the ckpt)
                self._opt_host_restore(host_snap)
                raise
            return None
        for c, v in zip(entry["cells"], new_state):
            c._data = v
        loss = NDArray(outs[0], entry["ctx"])
        if entry.get("has_fp"):
            from .resilience import integrity as _integrity

            self._last_fp_out = outs[entry["fp_idx"]]
            _integrity.note_fingerprint_step()
        else:
            self._last_fp_out = None
        # reading the flag is a host sync that breaks async dispatch
        # pipelining. Anything that GATES on it — sentinel, AMP scaler,
        # a halt/skip tap — reads it every step: the in-program select
        # and the host bookkeeping (the un-advance below, Adam's t /
        # num_update) must stay in lockstep, or the replayed scalar
        # operands would drift from the reverted device state. Only a
        # record-policy tap (pure telemetry, nothing gated) defers to
        # the sampling cadence, deriving its finite signal from the
        # sampled matrix's nonfinite column.
        need_flag = entry["has_flag"] and (
            self.sentinel is not None or entry["has_scale"]
            or entry["tap_gates"] or tap_sampled)
        if entry["has_tap"] and not need_flag:
            # record-policy tap (or gating tap off-cadence): the finite
            # signal derives from the sampled matrix's nonfinite column
            stats_np = np.asarray(outs[entry["tap_idx"]]) \
                if tap_sampled else None
            self.numerics.on_step(self._step_count, None, stats_np,
                                  (x_nd, y_nd))
        if need_flag:
            finite_ok = bool(np.asarray(outs[1]).reshape(-1)[0])
            norm_ok = (bool(np.asarray(outs[2]).reshape(-1)[0])
                       if entry["has_norm"] else None)
            if entry["has_scale"]:
                # the in-graph flag IS the AMP all-finite check: note it
                # so LossScaler.has_overflow never host-syncs under
                # capture (amp.unscale consumes the noted flag)
                self.loss_scaler.note_finite(finite_ok)
            tap_gated = entry["tap_gates"] and not finite_ok
            gated = (not finite_ok) if not checking \
                else not (finite_ok and norm_ok is not False)
            if (gated and (checking or entry["has_scale"])) or tap_gated:
                # the gated update never applied: un-advance the
                # replay's host bookkeeping (Adam's t, num_update)
                self._opt_host_restore(host_snap)
            self._apply_flag(finite_ok, norm_ok, checking)
            if entry["has_tap"]:
                stats_np = np.asarray(outs[entry["tap_idx"]]) \
                    if tap_sampled else None
                # emission + divergence detectors + non-finite policy;
                # off-cadence steps never pull the stats matrix (the
                # finite flag above is the only per-step host read)
                self.numerics.on_step(self._step_count, finite_ok,
                                      stats_np, (x_nd, y_nd))
        return loss

    def _apply_flag(self, finite_ok, norm_ok, checking):
        """Host-side policy application from the program's fused health
        flags — the captured counterpart of ``HealthSentinel
        .before_update`` (weights were already gated by the in-program
        select, so every policy only does bookkeeping/restore here).
        ``checking`` follows the sentinel's ``check_every`` cadence:
        off-cadence steps do no sentinel bookkeeping at all (eager
        ``before_update`` returns before looking at the gradients); a
        loss-scaler overflow is still recorded every step."""
        from .resilience import sentinel as _sentinel

        scaler = self.loss_scaler
        if scaler is not None:
            scaler.update_scale(not finite_ok)
        s = self.sentinel
        if s is None or not checking:
            if scaler is not None and not finite_ok:
                _sentinel.note_skip("amp_overflow")
            return
        ok = finite_ok and norm_ok is not False
        _sentinel.note_check(
            ok, kind="nonfinite" if not finite_ok else "grad_norm")
        if ok:
            return
        s.last_reason = (
            "non-finite gradient (NaN/Inf) (captured step)"
            if not finite_ok else
            f"global grad norm exceeds threshold "
            f"{s.grad_norm_threshold:.3e} (captured step)")
        if s.policy == "raise":
            raise _sentinel.NumericHealthError(
                f"numeric health check failed at captured step "
                f"{self._step_count}: {s.last_reason}")
        if s.policy == "skip_batch" or s.manager is None:
            _sentinel.note_skip("sentinel")
            return
        restored = s.manager.restore_latest(net=s._net or self.net,
                                            trainer=self.trainer)
        if restored is None:
            raise _sentinel.NumericHealthError(
                "rollback requested (captured step) but no valid "
                f"checkpoint exists under {s.manager.directory}")
        _sentinel.note_skip("sentinel")
        _sentinel.note_rollback()

    def _stall_rollback(self, err):
        """Mirror ``Trainer._stall_rollback`` for the captured call."""
        from .resilience import watchdog as _watchdog

        s = self.sentinel
        if s is None or s.policy != "rollback" or s.manager is None:
            return False
        manifest = s.manager.restore_latest(net=s._net or self.net,
                                            trainer=self.trainer)
        if manifest is None:
            return False
        _watchdog.note_rollback(err, manifest)
        import warnings

        warnings.warn(
            f"captured step stalled ({err}); rolled back to checkpoint "
            f"step {manifest.get('step')} and skipped the step")
        return True

    def _eager_step(self, x_nd, y_nd, batch_size):
        """The identical step semantics, eagerly (kill switch and
        capture-failure fallback): plain fwd/bwd + ``Trainer.step`` with
        the sentinel attached, exactly the pre-capture hot loop. With a
        loss scaler the captured data flow is replicated by hand (scale
        loss, unscale grads, fused finite check gating the update)."""
        import numpy as np

        from . import autograd

        trainer = self.trainer
        bs = batch_size if batch_size is not None else (
            self._batch_size if self._batch_size is not None
            else int(x_nd.shape[0]))
        scaler = self.loss_scaler
        if scaler is None:
            reattach = self.sentinel is not None \
                and trainer._sentinel is None
            if reattach:
                trainer._sentinel = self.sentinel
            try:
                with autograd.record():
                    loss = self.loss_fn(self.net(x_nd), y_nd)
                loss.backward()
                trainer.step(bs)
            finally:
                if reattach:
                    trainer._sentinel = None
            self._note_eager_fp()
            return loss
        from .resilience import faults as _faults
        from .resilience import watchdog as _watchdog

        scale = float(scaler.loss_scale)
        scaler.clear_note()  # stale captured-step flag never answers
        with autograd.record():  # this eager step's has_overflow
            loss = self.loss_fn(self.net(x_nd), y_nd)
            loss_b = loss * scale
        loss_b.backward()
        s = self.sentinel
        checking = False
        if s is not None:
            s._step += 1
            checking = (s._step - 1) % s.check_every == 0
        trainer._optimizer.rescale_grad = trainer._scale / bs
        # mirror gluon.Trainer.step's guard/fault points: the kill-switch
        # path must keep the watchdog deadline, hang/NaN drills, and
        # stall rollback the resilience stack promises for every step
        try:
            with _watchdog.guard("step", detail="capture._eager_step",
                                 step=getattr(s, "_step", None)):
                _faults.maybe_hang("hang_step")
                grads = self._grad_list()
                inv = 1.0 / scale
                for g in grads:
                    g._set_data((g * inv)._data)
                _faults.maybe_nan_grads(self.trainer._params)
                _faults.maybe_nonfinite_grad(self.trainer._params)
                finite_t, norm_t = self._health_flags(grads)
                finite_ok = bool(np.asarray(finite_t).reshape(-1)[0])
                norm_ok = (bool(np.asarray(norm_t).reshape(-1)[0])
                           if norm_t is not None else None)
                scaler.note_finite(finite_ok)
                ok = finite_ok and norm_ok is not False
                if finite_ok and (ok or not checking):
                    trainer._allreduce_grads()
                    trainer._update()
        except _watchdog.StallError as e:
            if not self._stall_rollback(e):
                raise
            return None
        self._apply_flag(finite_ok, norm_ok, checking)
        self._note_eager_fp()
        return loss


    def attach_monitor(self, monitor):
        """``Monitor.install`` entry point for the compiled-tap path:
        ensures this step has a :class:`~.observability.numerics
        .NumericsTap` (creating a ``record``-policy, request-driven one
        when none is armed — ``Monitor.tic`` forces the sample, so the
        tap's own cadence stays off) and returns it. Attaching a tap to
        an already-built step is a program change: the built entries
        are dropped with a structured retrace reason, never silently."""
        tap = self.numerics
        if tap is None:
            tap = _obs_numerics.NumericsTap(interval=0, policy="record")
            tap.bind(self.net, self.trainer)
            self.numerics = tap
            if self._entries:
                _note_retrace(self.label, self._last_sig, self._last_sig,
                              reason="numerics tap attached "
                                     "(Monitor install)")
                self._entries.clear()
        return tap


class CapturedShardedStep:
    """Captured view of a ``parallel.ShardedTrainer``: the trainer's
    fused step is already one donated pjit program, and every one of its
    step/grads/apply programs compiles through the capture path — AOT
    persistence, retrace forensics, capture counters — so this wrapper
    just counts steps and delegates (watchdog, elastic microbatching,
    mesh-shrink recovery all apply unchanged; an elastic or mesh
    re-capture shows up in :func:`retrace_log` instead of recompiling
    silently)."""

    def __init__(self, trainer, label="sharded_step"):
        self.trainer = trainer
        self.label = label
        # no executable invalidation needed: every ShardedTrainer step/
        # grads/apply program already compiles through _capture_exec, so
        # a pre-built (possibly minutes-of-XLA) executable is kept

    def __call__(self, x, y, microbatches=None, length=None):
        _STATS["capture_steps"] += 1
        return self.trainer.step(x, y, microbatches=microbatches,
                                 length=length)

    @property
    def mesh(self):
        return self.trainer.mesh

    @property
    def batch_sharding(self):
        """The trainer's batch placement, passed through so the
        streaming layer's ``DevicePrefetcher.for_trainer`` accepts a
        captured step wherever it accepts the trainer itself."""
        return self.trainer.batch_sharding


def capture(trainer, net=None, loss_fn=None, **kwargs):
    """Capture a whole training step as one donated XLA executable.

    ``capture(sharded_trainer)`` returns a :class:`CapturedShardedStep`;
    ``capture(trainer, net=net, loss_fn=loss)`` (gluon) returns a
    :class:`CapturedTrainerStep`. With ``MXNET_TPU_CAPTURE=0`` the gluon
    wrapper executes the identical step eagerly (kill switch).
    """
    from .parallel.trainer import ShardedTrainer

    if isinstance(trainer, ShardedTrainer):
        return CapturedShardedStep(trainer, **kwargs)
    if net is None or loss_fn is None:
        raise CaptureError(
            "capture(gluon_trainer) needs net= and loss_fn= (the step "
            "program is fwd+bwd+update, not just the update sweep)")
    return CapturedTrainerStep(net, loss_fn, trainer, **kwargs)
