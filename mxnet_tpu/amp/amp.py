"""Automatic mixed precision.

Parity: python/mxnet/contrib/amp/amp.py. The reference monkey-patches op
creators to insert amp_cast/amp_multicast symbols (amp.py:251); this build
hooks the single imperative dispatch chokepoint (imperative_invoke) instead:
with AMP active, inputs of MXU-bound ops are cast to the target dtype and
inputs of numerically-sensitive ops to fp32 (lists.py). Because hybridize /
mx.jit.trace re-run the imperative Python under jit, the same hook covers
compiled executables — the casts land inside the XLA graph and fuse away.

bf16 is the TPU-native target (same exponent range as fp32 → loss scaling
usually unnecessary); fp16 is supported for reference parity with the
dynamic LossScaler.
"""
from __future__ import annotations

import contextlib
import warnings

import numpy as _np

from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "amp_active",
           "cast_inputs_for"]

_STATE = {"active": False, "target_dtype": None, "target_ops": frozenset(),
          "fp32_ops": frozenset(), "widest_ops": frozenset(),
          "loss_scaler": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Turn on AMP (amp.py:251).

    target_dtype : 'bfloat16' (TPU-native) or 'float16' (reference parity).
    target_precision_ops / fp32_ops : override the default op lists.
    """
    import jax.numpy as jnp

    target_dtype = str(target_dtype)
    if target_dtype not in ("bfloat16", "float16"):
        raise ValueError("target_dtype must be bfloat16 or float16, got "
                         f"{target_dtype}")
    if conditional_fp32_ops:
        warnings.warn("conditional_fp32_ops is accepted for API parity but "
                      "treated as fp32_ops")
    fp32 = set(fp32_ops if fp32_ops is not None else lists.FP32_OPS)
    if conditional_fp32_ops:
        fp32.update(op for op, _, _ in conditional_fp32_ops)
    if target_dtype == "float16":
        fp32.update(lists.FP16_FP32_OPS)
    _STATE.update(
        active=True,
        target_dtype=jnp.bfloat16 if target_dtype == "bfloat16"
        else jnp.float16,
        target_ops=frozenset(target_precision_ops
                             if target_precision_ops is not None
                             else lists.TARGET_DTYPE_OPS),
        fp32_ops=frozenset(fp32),
        widest_ops=frozenset(lists.WIDEST_TYPE_CASTS),
        loss_scaler=LossScaler(
            init_scale=2. ** 16 if target_dtype == "float16" else 1.0),
    )


def reset():
    """Deactivate AMP (this build's extension; the reference has no off
    switch, but tests need one)."""
    _STATE.update(active=False, target_dtype=None,
                  target_ops=frozenset(), fp32_ops=frozenset(),
                  widest_ops=frozenset(), loss_scaler=None)


def amp_active():
    return _STATE["active"]


def cast_inputs_for(opname, in_arrays):
    """Dispatch hook: returns in_arrays cast per the active policy.
    Called from imperative_invoke; cheap no-op when AMP is off."""
    import jax.numpy as jnp

    if not _STATE["active"]:
        return in_arrays
    tgt = None
    if opname in _STATE["target_ops"]:
        tgt = _STATE["target_dtype"]
    elif opname in _STATE["fp32_ops"]:
        tgt = jnp.float32
    elif opname in _STATE["widest_ops"]:
        f_dtypes = [a.dtype for a in in_arrays
                    if hasattr(a, "dtype")
                    and jnp.issubdtype(a.dtype, jnp.floating)]
        if len(set(map(str, f_dtypes))) > 1:
            tgt = jnp.result_type(*f_dtypes)
    if tgt is None:
        return in_arrays
    return [a.astype(tgt)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            and a.dtype != tgt else a
            for a in in_arrays]


def init_trainer(trainer):
    """Attach the loss scaler to a gluon Trainer (amp.py init_trainer)."""
    if not _STATE["active"]:
        raise RuntimeError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = _STATE["loss_scaler"]
    trainer._amp_original_scale = trainer._scale


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale loss before backward; arrange for gradient unscaling in
    trainer.step (amp.py scale_loss)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    # a fresh eager step begins: a finite flag noted by a captured step
    # is about ITS gradients — never let it answer this step's unscale
    scaler.clear_note()
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Explicitly check overflow + update the dynamic scale; returns True
    if this step's gradients are safe to apply. Overflow skips land on
    the same ``health_skipped_steps`` counter as resilience sentinel
    skips (profiler.dispatch_stats()), so 'unhealthy steps' is one
    series regardless of which guardrail caught it."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return True
    params = [p for p in trainer._params if p.grad_req != "null"]
    overflow = scaler.has_overflow(params)
    scaler.update_scale(overflow)
    if overflow:
        from ..resilience.sentinel import note_skip

        note_skip("amp_overflow")
    return not overflow


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None, cast_optional_params=False):
    """Cast a symbolic model's params for low-precision inference
    (amp.py convert_model). The symbol itself is unchanged — ops follow
    their input dtypes in this build's executor."""
    import numpy as np

    tgt = _np.dtype("float16") if target_dtype == "float16" else "bfloat16"
    new_args = {k: v.astype(tgt) for k, v in arg_params.items()}
    new_aux = {k: v.astype(tgt) for k, v in aux_params.items()}
    return sym, new_args, new_aux


def convert_hybrid_block(block, target_dtype="bfloat16", **kwargs):
    """Cast a HybridBlock's params in place for low-precision inference
    (amp.py convert_hybrid_block)."""
    block.cast(target_dtype)
    return block
