"""Dynamic loss scaling.

Parity: python/mxnet/contrib/amp/loss_scaler.py — scale the loss up before
backward so fp16 gradients don't flush to zero, check for inf/nan with the
fused all_finite kernel (src/operator/contrib/all_finite.cc), and adapt the
scale (halve on overflow, double every ``scale_window`` clean steps).
bf16 shares fp32's exponent range, so bf16 training normally runs with
scale 1.0 and this class matters for fp16 parity.
"""
from __future__ import annotations

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2. ** 16, scale_factor=2.,
                 scale_window=2000, tolerance=0.):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0
        self._noted_finite = None

    def note_finite(self, finite):
        """Captured-step hook: the whole-program capture computes the
        fused all-finite check INSIDE its donated executable (one side
        output next to the numerics telemetry) and notes the result
        here, so the next :meth:`has_overflow` consumes the flag
        instead of re-running the kernel and paying a per-step
        ``.asnumpy()`` host sync. Never called on the eager path, whose
        behavior stays bitwise-identical."""
        self._noted_finite = bool(finite)

    def clear_note(self):
        """Invalidate any unconsumed noted flag. Called at the start of
        an EAGER step (``amp.scale_loss``, the captured step's eager
        fallback): a flag noted by a previous captured step describes
        that step's gradients, and must never answer ``has_overflow``
        for a fresh eager backward."""
        self._noted_finite = None

    def has_overflow(self, params):
        """True if any gradient in ``params`` (list of Parameter or NDArray)
        contains inf/nan. Uses the fused multi_all_finite kernel — or,
        under whole-program capture, the flag the captured step already
        computed in-graph (``note_finite``), consumed once: no second
        kernel launch, no host sync."""
        noted = self._noted_finite
        if noted is not None:
            self._noted_finite = None
            return not noted
        from ..ndarray import ndarray as _nd

        grads = []
        for p in params:
            g = getattr(p, "_grad", None)
            if isinstance(g, _nd.NDArray):
                grads.append(g)
            elif isinstance(g, (list, tuple)) and g:
                grads.extend(g)
            elif hasattr(p, "list_grad"):
                try:
                    grads.extend(p.list_grad())
                except Exception:
                    pass
            elif isinstance(p, _nd.NDArray):
                grads.append(p)
        if not grads:
            return False
        finite = _nd.imperative_invoke(
            "multi_all_finite", *grads, num_arrays=len(grads))[0]
        return not bool(finite.asnumpy().reshape(-1)[0])

    def update_scale(self, overflow):
        """Dynamic adjustment (loss_scaler.py update_scale)."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
