"""AMP op categorization.

Parity: python/mxnet/contrib/amp/lists/symbol.py — which ops run in the
low-precision target dtype, which are pinned to fp32, and which follow
their inputs. TPU-native: bf16 is the native MXU dtype, so the target list
is the MXU-bound ops (matmul/conv families); the fp32 list is reductions
and exp/log-shaped numerics where bf16's 8-bit mantissa visibly hurts.
Everything unlisted is dtype-following (elementwise ops run in whatever
dtype arrives).
"""

# run in the target dtype (bf16/fp16): MXU-bound compute
TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "RNN",
    "dot", "batch_dot",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "_contrib_interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt",
]

# pinned to fp32: reductions / exp-log numerics
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxOutput", "SoftmaxActivation",
    "exp", "log", "log2", "log10", "log1p", "expm1",
    "sum", "mean", "prod", "nansum", "nanprod", "norm",
    "L2Normalization", "InstanceNorm", "LayerNorm", "GroupNorm", "LRN",
    "make_loss", "MakeLoss", "smooth_l1", "CTCLoss",
    "linalg_gemm", "linalg_gemm2", "linalg_potrf", "linalg_trsm",
    "power", "rsqrt", "sqrt", "square", "reciprocal",
]

# kept in fp32 only under fp16 (bf16 has fp32's range, fp16 does not)
FP16_FP32_OPS = [
    "BatchNorm", "cumsum",
]

# ops whose float inputs must all agree — cast to the widest
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "Concat", "concat", "stack", "where",
]
