"""mx.amp — automatic mixed precision (reference: python/mxnet/contrib/amp)."""
from .amp import (init, init_trainer, scale_loss, unscale, convert_model,
                  convert_hybrid_block, amp_active, cast_inputs_for, reset)
from .loss_scaler import LossScaler
from . import lists
