"""Stateful random number API over an explicit key cell.

Parity: python/mxnet/random.py + src/common/random_generator.h. The global
generator is an NDArray holding a jax PRNG key; every sampling op takes the
key as a mutable input and writes back the split key (SURVEY.md §7.8:
"wrap a global threaded key-stream to preserve the API"). Because the key is
an ordinary mutable cell, `mx.jit.trace` captures it as threaded state and
sampling remains correct across steps inside one compiled executable.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["seed", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "multinomial", "shuffle", "bernoulli",
           "generator_key"]

_KEY = None


def _key_cell():
    global _KEY
    if _KEY is None:
        seed(_np.random.randint(0, 2**31 - 1))
    return _KEY


def generator_key():
    """The global key cell (NDArray) — pass to ops needing randomness."""
    return _key_cell()


def seed(seed_state, ctx="all"):
    """Parity: mx.random.seed."""
    global _KEY
    import jax

    from .ndarray.ndarray import NDArray

    raw = jax.random.PRNGKey(int(seed_state))
    if _KEY is None:
        _KEY = NDArray(raw)
    else:
        _KEY._set_data(raw)


def _invoke(opname, *arrays, ctx=None, out=None, **kw):
    """Dispatch a sampling op placed on ``ctx`` (or ``out``'s context, or the
    current context) — NOT on the key cell's device, which is wherever the
    previous sample ran."""
    from .context import current_context
    from .ndarray.ndarray import imperative_invoke

    if ctx is None:
        ctx = out.context if out is not None else current_context()
    r = imperative_invoke(opname, *arrays, ctx=ctx, **kw)[0]
    if out is not None:
        out._set_data(r._data)
        return out
    return r


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray.ndarray import NDArray

    if isinstance(low, NDArray):
        return _invoke("_sample_uniform", low, high, _key_cell(),
                       shape=_shape(shape), dtype=dtype, ctx=ctx)
    return _invoke("_random_uniform", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), low=float(low), high=float(high),
                   ctx=ctx, out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray.ndarray import NDArray

    if isinstance(loc, NDArray):
        return _invoke("_sample_normal", loc, scale, _key_cell(),
                       shape=_shape(shape), dtype=dtype, ctx=ctx)
    return _invoke("_random_normal", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), loc=float(loc), scale=float(scale),
                   ctx=ctx, out=out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype, ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None):
    return _invoke("_random_randint", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), low=int(low), high=int(high), ctx=ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None):
    from .ndarray.ndarray import NDArray

    if isinstance(alpha, NDArray):
        return _invoke("_sample_gamma", alpha, beta, _key_cell(),
                       shape=_shape(shape), dtype=dtype, ctx=ctx)
    return _invoke("_random_gamma", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), alpha=float(alpha), beta=float(beta),
                   ctx=ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None):
    return _invoke("_random_exponential", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), lam=1.0 / float(scale), ctx=ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None):
    return _invoke("_random_poisson", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), lam=float(lam), ctx=ctx)


def bernoulli(p=0.5, shape=None, dtype="float32", ctx=None):
    return _invoke("_random_bernoulli", _key_cell(), shape=_shape(shape),
                   dtype=str(dtype), p=float(p), ctx=ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32"):
    return _invoke("_sample_multinomial", data, _key_cell(),
                   shape=_shape(shape), get_prob=get_prob, dtype=str(dtype))


def shuffle(data, out=None):
    return _invoke("_shuffle", data, _key_cell(), ctx=data.context, out=out)
