"""Device contexts.

Parity with the reference's Context (include/mxnet/base.h:74-200,
python/mxnet/context.py): `cpu()`, `tpu()` (first-class, the north star),
plus `gpu()` as an alias for the local accelerator so reference scripts run
unmodified. A Context maps onto a concrete `jax.Device`; storage placement
goes through PJRT via `jax.device_put` rather than a custom allocator —
HBM pooling, streams and copy engines are PJRT's job.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]


def _jax():
    import jax

    return jax


class Context:
    """A device context. devtype: cpu=1, gpu=2 (alias->accelerator), cpu_pinned=3, tpu=13."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 13: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._jax_device = None

    @property
    def device_type(self):
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    __repr__ = __str__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *args):
        Context._default_ctx.stack.pop()

    # -- jax bridge ------------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device (PJRT device)."""
        if self._jax_device is not None:
            return self._jax_device
        jax = _jax()
        dt = self.device_type
        if dt in ("cpu", "cpu_pinned", "cpu_shared"):
            # Addressable devices only: under jax.distributed, jax.devices()
            # is the GLOBAL list and device 0 may belong to another process.
            devs = [d for d in jax.devices("cpu")
                    if d.process_index == jax.process_index()]
        else:  # tpu / gpu both mean "the local accelerator"
            devs = _accelerator_devices()
            if devs:
                local = [d for d in devs
                         if d.process_index == jax.process_index()]
                devs = local or devs
            if not devs:
                # Fall back to whatever the default platform offers (CPU when
                # running the test suite with JAX_PLATFORMS=cpu).
                devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: only {len(devs)} device(s) of this type are visible"
            )
        self._jax_device = devs[self.device_id]
        return self._jax_device

    def empty_cache(self):
        """Parity: Context.empty_cache (pooled allocator flush). PJRT manages
        the HBM pool; this is a best-effort hint."""
        import gc

        gc.collect()


def _accelerator_devices():
    jax = _jax()
    devs = [d for d in jax.devices() if d.platform not in ("cpu",)]
    return devs


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias context for the local accelerator (reference scripts use mx.gpu())."""
    return Context("gpu", device_id)


def num_tpus():
    return len(_accelerator_devices())


def num_gpus():
    return num_tpus()


def current_context():
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def context_from_jax_device(dev):
    """Inverse mapping jax.Device -> Context."""
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    accel = _accelerator_devices()
    for i, d in enumerate(accel):
        if d == dev:
            return Context("tpu", i)
    return Context("tpu", getattr(dev, "id", 0))
