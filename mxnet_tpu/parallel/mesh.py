"""Device mesh helpers.

The TPU-native replacement for the reference's device topology machinery
(src/kvstore/gpu_topology.h link discovery, CommDeviceTree): on TPU the
topology is a named mesh and XLA chooses collective algorithms over ICI/DCN.
Axis convention (scaling-book style): 'dp' data, 'tp' tensor/model, 'pp'
pipeline, 'sp' sequence/context, 'ep' expert.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["create_mesh", "default_mesh", "local_devices", "AXES"]

AXES = ("dp", "tp", "pp", "sp", "ep")


def local_devices(platform=None):
    import jax

    return jax.devices(platform) if platform else jax.devices()


def create_mesh(axes=None, devices=None):
    """Create a jax.sharding.Mesh.

    axes: dict axis-name -> size (a -1 size absorbs remaining devices),
          or None for a pure data-parallel mesh over all devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    assert total == len(devices), \
        f"mesh {dict(zip(names, sizes))} needs {total} devices, " \
        f"got {len(devices)}"
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh(n_devices=None):
    import jax

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return create_mesh({"dp": len(devs)}, devs)


def shard_map(fn, mesh, in_specs, out_specs, check=True):
    """Version-portable jax shard_map: jax >= 0.6 exposes `jax.shard_map`
    with the replication check named check_vma; earlier releases ship it
    as jax.experimental.shard_map with check_rep."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _smap

    return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=check)
