"""Device mesh helpers.

The TPU-native replacement for the reference's device topology machinery
(src/kvstore/gpu_topology.h link discovery, CommDeviceTree): on TPU the
topology is a named mesh and XLA chooses collective algorithms over ICI/DCN.
Axis convention (scaling-book style): 'dp' data, 'tp' tensor/model, 'pp'
pipeline, 'sp' sequence/context, 'ep' expert.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["create_mesh", "default_mesh", "local_devices", "shrink_mesh",
           "MeshShrinkError", "AXES"]

AXES = ("dp", "tp", "pp", "sp", "ep")


def local_devices(platform=None):
    import jax

    return jax.devices(platform) if platform else jax.devices()


def create_mesh(axes=None, devices=None):
    """Create a jax.sharding.Mesh.

    axes: dict axis-name -> size (a -1 size absorbs remaining devices),
          or None for a pure data-parallel mesh over all devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    assert total == len(devices), \
        f"mesh {dict(zip(names, sizes))} needs {total} devices, " \
        f"got {len(devices)}"
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


class MeshShrinkError(RuntimeError):
    """No viable smaller mesh exists after excising the dead ranks."""


def shrink_mesh(mesh, dead_ranks, batch_axis="dp"):
    """The largest viable mesh buildable from the survivors after losing
    ``dead_ranks`` along ``batch_axis`` — the topology half of elastic
    peer-loss recovery (resilience/elastic.py; the state half is the
    reshardable checkpoint restore).

    Ranks map onto ``batch_axis`` slots (on a one-device-per-process dp
    mesh a rank IS its dp coordinate; ranks outside the axis still cost
    a slot each, dropped from the tail). Every non-batch axis keeps its
    full extent — losing a dp peer must not silently shrink tp/pp — and
    the new batch extent is the largest power of two that fits the
    survivors, so dp=8 degrades 8 -> 4 -> 2 -> 1 and batch divisibility
    (rows % dp) is preserved for power-of-two batches. Raises
    MeshShrinkError when nothing viable remains.
    """
    from jax.sharding import Mesh

    names = list(mesh.axis_names)
    if batch_axis not in names:
        raise MeshShrinkError(
            f"mesh {names} has no '{batch_axis}' axis to shrink")
    axis = names.index(batch_axis)
    size = int(mesh.devices.shape[axis])
    dead = {int(r) for r in dead_ranks}
    if not dead:
        raise MeshShrinkError("no dead ranks to excise")
    in_range = sorted(r for r in dead if 0 <= r < size)
    extra = len(dead) - len(in_range)
    slots = [i for i in range(size) if i not in in_range]
    if extra:  # ranks we can't map onto the axis still each cost a slot
        slots = slots[:max(0, len(slots) - extra)]
    if not slots:
        raise MeshShrinkError(
            f"all {size} '{batch_axis}' slots lost ranks; no survivors "
            "to rebuild a mesh from")
    new_size = 1 << (len(slots).bit_length() - 1)
    if new_size >= size:
        raise MeshShrinkError(
            f"'{batch_axis}' cannot shrink below its current size {size}")
    devices = np.take(mesh.devices, slots[:new_size], axis=axis)
    return Mesh(devices, tuple(names))


def default_mesh(n_devices=None):
    import jax

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return create_mesh({"dp": len(devs)}, devs)


def shard_map(fn, mesh, in_specs, out_specs, check=True):
    """Version-portable jax shard_map: jax >= 0.6 exposes `jax.shard_map`
    with the replication check named check_vma; earlier releases ship it
    as jax.experimental.shard_map with check_rep."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _smap

    return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=check)
