"""Device mesh helpers.

The TPU-native replacement for the reference's device topology machinery
(src/kvstore/gpu_topology.h link discovery, CommDeviceTree): on TPU the
topology is a named mesh and XLA chooses collective algorithms over ICI/DCN.
Axis convention (scaling-book style): 'dp' data, 'fsdp' fully-sharded data,
'tp' tensor/model, 'pp' pipeline, 'sp' sequence/context, 'ep' expert.
"""
from __future__ import annotations

import math
import os

import numpy as np

__all__ = ["create_mesh", "default_mesh", "named_mesh", "parse_mesh_spec",
           "local_devices", "shrink_mesh", "MeshShrinkError", "AXES"]

AXES = ("dp", "fsdp", "tp", "pp", "sp", "ep")


def local_devices(platform=None):
    import jax

    return jax.devices(platform) if platform else jax.devices()


def create_mesh(axes=None, devices=None):
    """Create a jax.sharding.Mesh.

    axes: dict axis-name -> size (a -1 size absorbs remaining devices),
          or None for a pure data-parallel mesh over all devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    assert total == len(devices), \
        f"mesh {dict(zip(names, sizes))} needs {total} devices, " \
        f"got {len(devices)}"
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


class MeshShrinkError(RuntimeError):
    """No viable smaller mesh exists after excising the dead ranks.

    Structured: carries the old mesh shape (``axes``), the ranks that
    died (``dead_ranks``) and the axis that was being shrunk
    (``batch_axis``) so recovery code and crash reports can say exactly
    why the topology could not be rebuilt.
    """

    def __init__(self, msg, *, axes=None, dead_ranks=(), batch_axis=None):
        super().__init__(msg)
        self.axes = dict(axes or {})
        self.dead_ranks = tuple(dead_ranks)
        self.batch_axis = batch_axis


def shrink_mesh(mesh, dead_ranks, batch_axis="dp"):
    """The largest viable mesh buildable from the survivors after losing
    ``dead_ranks`` along the (data-parallel) shrink axis — the topology
    half of elastic peer-loss recovery (resilience/elastic.py; the state
    half is the reshardable checkpoint restore).

    ``batch_axis`` may be one axis name or a tuple of names (the batch
    dimension of a dp×fsdp mesh is sharded over both); shrinking always
    happens along the FIRST name — the outermost data axis — and every
    other axis keeps its full extent, because losing a dp peer must not
    silently change the fsdp/tp layout the parameters are sharded over.

    On a one-axis mesh a rank IS its slot coordinate. On a multi-axis
    mesh a rank is the flat device ordinal in ``mesh.devices`` (C
    order): its shrink-axis coordinate names the slot lost, and the
    WHOLE slot — the full fsdp×tp slice that peer participated in — is
    excised. Ranks outside the device range still cost a slot each,
    dropped from the tail. The new extent is the largest power of two
    that fits the survivors, so dp=8 degrades 8 -> 4 -> 2 -> 1 and
    batch divisibility (rows % dp) is preserved for power-of-two
    batches. Raises a structured MeshShrinkError when the survivors
    cannot rebuild a mesh that still tiles the non-batch axes.
    """
    from jax.sharding import Mesh

    names = list(mesh.axis_names)
    old_axes = dict(zip(names, mesh.devices.shape))
    shrink_axes = ((batch_axis,) if isinstance(batch_axis, str)
                   else tuple(batch_axis))
    shrink_axis = shrink_axes[0]
    if shrink_axis not in names:
        raise MeshShrinkError(
            f"mesh {names} has no '{shrink_axis}' axis to shrink",
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    axis = names.index(shrink_axis)
    size = int(mesh.devices.shape[axis])
    dead = {int(r) for r in dead_ranks}
    if not dead:
        raise MeshShrinkError("no dead ranks to excise",
                              axes=old_axes, batch_axis=shrink_axis)
    total = int(mesh.devices.size)
    if total == size:  # one-axis fast path: rank IS the slot coordinate
        in_range = sorted(r for r in dead if 0 <= r < size)
        lost_slots = set(in_range)
        extra = len(dead) - len(in_range)
    else:  # multi-axis: rank = flat device ordinal -> shrink-axis slot
        in_range = sorted(r for r in dead if 0 <= r < total)
        lost_slots = {
            int(np.unravel_index(r, mesh.devices.shape)[axis])
            for r in in_range}
        extra = len(dead) - len(in_range)
    slots = [i for i in range(size) if i not in lost_slots]
    if extra:  # ranks we can't map onto the axis still each cost a slot
        slots = slots[:max(0, len(slots) - extra)]
    non_batch = {n: s for n, s in old_axes.items() if n != shrink_axis}
    if not slots:
        raise MeshShrinkError(
            f"all {size} '{shrink_axis}' slots lost ranks; no survivors "
            "to rebuild a mesh from"
            + (f" (non-batch axes {non_batch} left untiled)"
               if non_batch else ""),
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    new_size = 1 << (len(slots).bit_length() - 1)
    if new_size >= size:
        raise MeshShrinkError(
            f"'{shrink_axis}' cannot shrink below its current size {size}"
            + (f"; survivors cannot re-tile the non-batch axes "
               f"{non_batch} at a smaller extent" if non_batch else ""),
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    devices = np.take(mesh.devices, slots[:new_size], axis=axis)
    return Mesh(devices, tuple(names))


def default_mesh(n_devices=None):
    import jax

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return create_mesh({"dp": len(devs)}, devs)


def parse_mesh_spec(spec):
    """Parse a 'dp=2,fsdp=2,tp=-1' mesh-shape string into an ordered
    axis dict (a -1 size absorbs the remaining devices, create_mesh
    semantics). Axis names must come from AXES so a typo'd axis fails
    loudly instead of silently replicating."""
    axes = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh axis {part!r} in {spec!r}: want name=size")
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} in {spec!r}: want one of {AXES}")
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r} in {spec!r}")
        axes[name] = int(val)
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def named_mesh(spec=None, devices=None):
    """The named multi-axis training mesh (docs/parallel.md).

    ``spec`` is a 'dp=2,fsdp=2,tp=2' string, an axis dict, or None to
    read the ``MXNET_TPU_MESH_SHAPE`` env knob; with neither set this
    degrades to the pure data-parallel default_mesh so single-axis
    callers need no configuration. Axes with size 1 are kept — a
    dp=2,fsdp=1,tp=4 mesh still names all three axes so SpecLayout
    rules resolve uniformly.
    """
    if spec is None:
        spec = os.environ.get("MXNET_TPU_MESH_SHAPE", "").strip()
        if not spec:
            return default_mesh() if devices is None else create_mesh(
                {"dp": len(list(devices))}, devices)
    axes = spec if isinstance(spec, dict) else parse_mesh_spec(spec)
    return create_mesh(axes, devices)


def shard_map(fn, mesh, in_specs, out_specs, check=True):
    """Version-portable jax shard_map: jax >= 0.6 exposes `jax.shard_map`
    with the replication check named check_vma; earlier releases ship it
    as jax.experimental.shard_map with check_rep."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _smap

    return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=check)
