"""Device mesh helpers.

The TPU-native replacement for the reference's device topology machinery
(src/kvstore/gpu_topology.h link discovery, CommDeviceTree): on TPU the
topology is a named mesh and XLA chooses collective algorithms over ICI/DCN.
Axis convention (scaling-book style): 'dp' data, 'fsdp' fully-sharded data,
'tp' tensor/model, 'pp' pipeline, 'sp' sequence/context, 'ep' expert.
"""
from __future__ import annotations

import math
import os

import numpy as np

__all__ = ["create_mesh", "default_mesh", "named_mesh", "parse_mesh_spec",
           "local_devices", "shrink_mesh", "MeshShrinkError", "AXES",
           "PodTopology", "pod_mesh", "shrink_mesh_hosts"]

AXES = ("dp", "fsdp", "tp", "pp", "sp", "ep")


def local_devices(platform=None):
    import jax

    return jax.devices(platform) if platform else jax.devices()


def create_mesh(axes=None, devices=None):
    """Create a jax.sharding.Mesh.

    axes: dict axis-name -> size (a -1 size absorbs remaining devices),
          or None for a pure data-parallel mesh over all devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    assert total == len(devices), \
        f"mesh {dict(zip(names, sizes))} needs {total} devices, " \
        f"got {len(devices)}"
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


class MeshShrinkError(RuntimeError):
    """No viable smaller mesh exists after excising the dead ranks.

    Structured: carries the old mesh shape (``axes``), the ranks that
    died (``dead_ranks``) and the axis that was being shrunk
    (``batch_axis``) so recovery code and crash reports can say exactly
    why the topology could not be rebuilt.
    """

    def __init__(self, msg, *, axes=None, dead_ranks=(), batch_axis=None):
        super().__init__(msg)
        self.axes = dict(axes or {})
        self.dead_ranks = tuple(dead_ranks)
        self.batch_axis = batch_axis


def shrink_mesh(mesh, dead_ranks, batch_axis="dp"):
    """The largest viable mesh buildable from the survivors after losing
    ``dead_ranks`` along the (data-parallel) shrink axis — the topology
    half of elastic peer-loss recovery (resilience/elastic.py; the state
    half is the reshardable checkpoint restore).

    ``batch_axis`` may be one axis name or a tuple of names (the batch
    dimension of a dp×fsdp mesh is sharded over both); shrinking always
    happens along the FIRST name — the outermost data axis — and every
    other axis keeps its full extent, because losing a dp peer must not
    silently change the fsdp/tp layout the parameters are sharded over.

    On a one-axis mesh a rank IS its slot coordinate. On a multi-axis
    mesh a rank is the flat device ordinal in ``mesh.devices`` (C
    order): its shrink-axis coordinate names the slot lost, and the
    WHOLE slot — the full fsdp×tp slice that peer participated in — is
    excised. Ranks outside the device range still cost a slot each,
    dropped from the tail. The new extent is the largest power of two
    that fits the survivors, so dp=8 degrades 8 -> 4 -> 2 -> 1 and
    batch divisibility (rows % dp) is preserved for power-of-two
    batches. Raises a structured MeshShrinkError when the survivors
    cannot rebuild a mesh that still tiles the non-batch axes.
    """
    from jax.sharding import Mesh

    names = list(mesh.axis_names)
    old_axes = dict(zip(names, mesh.devices.shape))
    shrink_axes = ((batch_axis,) if isinstance(batch_axis, str)
                   else tuple(batch_axis))
    shrink_axis = shrink_axes[0]
    if shrink_axis not in names:
        raise MeshShrinkError(
            f"mesh {names} has no '{shrink_axis}' axis to shrink",
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    axis = names.index(shrink_axis)
    size = int(mesh.devices.shape[axis])
    dead = {int(r) for r in dead_ranks}
    if not dead:
        raise MeshShrinkError("no dead ranks to excise",
                              axes=old_axes, batch_axis=shrink_axis)
    total = int(mesh.devices.size)
    if total == size:  # one-axis fast path: rank IS the slot coordinate
        in_range = sorted(r for r in dead if 0 <= r < size)
        lost_slots = set(in_range)
        extra = len(dead) - len(in_range)
    else:  # multi-axis: rank = flat device ordinal -> shrink-axis slot
        in_range = sorted(r for r in dead if 0 <= r < total)
        lost_slots = {
            int(np.unravel_index(r, mesh.devices.shape)[axis])
            for r in in_range}
        extra = len(dead) - len(in_range)
    slots = [i for i in range(size) if i not in lost_slots]
    if extra:  # ranks we can't map onto the axis still each cost a slot
        slots = slots[:max(0, len(slots) - extra)]
    non_batch = {n: s for n, s in old_axes.items() if n != shrink_axis}
    if not slots:
        raise MeshShrinkError(
            f"all {size} '{shrink_axis}' slots lost ranks; no survivors "
            "to rebuild a mesh from"
            + (f" (non-batch axes {non_batch} left untiled)"
               if non_batch else ""),
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    new_size = 1 << (len(slots).bit_length() - 1)
    if new_size >= size:
        raise MeshShrinkError(
            f"'{shrink_axis}' cannot shrink below its current size {size}"
            + (f"; survivors cannot re-tile the non-batch axes "
               f"{non_batch} at a smaller extent" if non_batch else ""),
            axes=old_axes, dead_ranks=dead_ranks, batch_axis=shrink_axis)
    devices = np.take(mesh.devices, slots[:new_size], axis=axis)
    return Mesh(devices, tuple(names))


class PodTopology:
    """The pod's host failure domains: which devices belong to which host.

    A "host" is the unit that fails together — one process of a real
    multi-host job (``jax.distributed``), or one virtual group of
    ``devices_per_host`` consecutive devices in the single-process
    simulated pod CI runs on (``MXNET_TPU_POD_HOSTS`` virtual hosts over
    the forced CPU devices). Everything host-domain-aware — the
    host-slice mesh shrink, the distributed checkpoint commit, the
    watchdog's pod liveness — consumes this one mapping, so the two
    modes exercise the same code paths.

    ``devices`` is the HOST-MAJOR device order the pod mesh is built
    over: host h owns the contiguous flat ordinals
    ``[h*devices_per_host, (h+1)*devices_per_host)``.
    """

    def __init__(self, num_hosts, devices_per_host, this_host=0,
                 devices=None, simulated=True):
        self.num_hosts = int(num_hosts)
        self.devices_per_host = int(devices_per_host)
        self.this_host = int(this_host)
        self.simulated = bool(simulated)
        self.devices = list(devices) if devices is not None else None
        if self.num_hosts <= 0 or self.devices_per_host <= 0:
            raise ValueError(
                f"pod needs positive host/device counts, got "
                f"{num_hosts} hosts x {devices_per_host} devices")
        if not 0 <= self.this_host < self.num_hosts:
            raise ValueError(
                f"this_host={this_host} out of range for "
                f"{num_hosts}-host pod")

    @classmethod
    def detect(cls, devices=None):
        """The running job's topology: real multi-process (one host per
        jax process) when ``jax.process_count() > 1``; otherwise a
        simulated pod over the local devices with ``MXNET_TPU_POD_HOSTS``
        virtual hosts (default 1 — a single-host "pod")."""
        import jax

        if devices is None:
            devices = jax.devices()
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
        if jax.process_count() > 1:
            per = {}
            for d in devices:
                per.setdefault(d.process_index, []).append(d)
            counts = {len(v) for v in per.values()}
            if len(counts) != 1:
                raise ValueError(
                    f"uneven pod: per-host device counts {sorted(counts)}")
            return cls(len(per), counts.pop(),
                       this_host=jax.process_index(), devices=devices,
                       simulated=False)
        hosts = int(os.environ.get("MXNET_TPU_POD_HOSTS", "1"))
        return cls.simulated(hosts, devices)

    @classmethod
    def simulated(cls, num_hosts, devices=None):
        """Partition the local devices into ``num_hosts`` virtual hosts
        of equal size (the CI pod: N virtual hosts x M forced CPU
        devices in one process)."""
        import jax

        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        num_hosts = int(num_hosts)
        if num_hosts <= 0 or len(devices) % num_hosts:
            raise ValueError(
                f"{len(devices)} devices do not split into {num_hosts} "
                "equal virtual hosts")
        return cls(num_hosts, len(devices) // num_hosts, this_host=0,
                   devices=devices, simulated=True)

    @property
    def total_devices(self):
        return self.num_hosts * self.devices_per_host

    def host_of(self, ordinal):
        """Host index owning flat (host-major) device ordinal."""
        return int(ordinal) // self.devices_per_host

    def host_ordinals(self, host):
        """The flat device ordinals host ``host`` owns."""
        host = int(host)
        if not 0 <= host < self.num_hosts:
            raise ValueError(f"host {host} out of range for "
                             f"{self.num_hosts}-host pod")
        start = host * self.devices_per_host
        return tuple(range(start, start + self.devices_per_host))

    def host_of_device(self, device):
        """Host index owning a jax device (real mode: its process;
        simulated mode: position in the host-major device order)."""
        if not self.simulated:
            return int(device.process_index)
        if self.devices is None:
            raise ValueError("simulated topology built without devices")
        for i, d in enumerate(self.devices):
            if d is device or d.id == device.id:
                return self.host_of(i)
        raise ValueError(f"device {device} is not part of this pod")

    def hosts(self):
        return tuple(range(self.num_hosts))

    def shrunk(self, kept_hosts):
        """The topology after excising every host not in ``kept_hosts``
        (survivor hosts are renumbered 0..k-1 in their original order)."""
        kept = sorted(int(h) for h in kept_hosts)
        if self.this_host in kept:
            new_this = kept.index(self.this_host)
        else:
            new_this = 0  # a dead host's own process never gets here
        devices = None
        if self.devices is not None:
            devices = [self.devices[o] for h in kept
                       for o in self.host_ordinals(h)]
        return PodTopology(len(kept), self.devices_per_host,
                           this_host=new_this, devices=devices,
                           simulated=self.simulated)

    def describe(self):
        return {"num_hosts": self.num_hosts,
                "devices_per_host": self.devices_per_host,
                "this_host": self.this_host,
                "simulated": self.simulated}

    def __repr__(self):
        return (f"PodTopology(hosts={self.num_hosts}, "
                f"devices_per_host={self.devices_per_host}, "
                f"this_host={self.this_host}, "
                f"simulated={self.simulated})")


def pod_mesh(axes=None, topology=None):
    """The global named mesh of a pod, in HOST-MAJOR device order, plus
    its topology: host h's devices occupy the contiguous flat ordinals
    ``[h*M, (h+1)*M)`` of ``mesh.devices`` (C order), so a whole host
    maps onto whole slots of some named axis and ``shrink_mesh_hosts``
    can excise it. Returns ``(mesh, topology)``.

    ``axes`` defaults to pure data parallelism over every device in the
    pod. On a real multi-host job every process builds the SAME global
    mesh (same device order — sorted by (process, id)); in the simulated
    pod the host-major order is simply the local device list.
    """
    if topology is None:
        topology = PodTopology.detect()
    devices = topology.devices
    if devices is None:
        import jax

        devices = sorted(jax.devices(),
                         key=lambda d: (d.process_index, d.id))
        topology.devices = list(devices)
    if axes is None:
        axes = {"dp": len(devices)}
    return create_mesh(axes, devices), topology


def _axis_slot_ordinals(shape, axis):
    """slot -> frozenset of flat (C-order) ordinals in that slot of
    ``axis`` for a mesh of the given shape."""
    ordinals = np.arange(int(np.prod(shape))).reshape(shape)
    moved = np.moveaxis(ordinals, axis, 0)
    return [frozenset(int(o) for o in moved[s].ravel())
            for s in range(shape[axis])]


def shrink_mesh_hosts(mesh, dead_hosts, topology, batch_axis="dp"):
    """Excise entire hosts from a host-major pod mesh: the host-domain
    generalization of :func:`shrink_mesh` (which excises one rank's slot
    along the batch axis). A dead HOST takes all of its devices with it,
    wherever they sit in the mesh — so the shrink axis is chosen as the
    first named axis (batch axis preferred, then mesh order) whose slots
    the dead hosts' device set exactly tiles. The surviving extent on
    that axis is trimmed to the largest power of two (same degrade
    ladder and batch-divisibility contract as ``shrink_mesh``).

    Returns ``(new_mesh, new_topology, kept_hosts)`` where
    ``kept_hosts`` are the ORIGINAL host indices that survived into the
    new mesh (in order) and ``new_topology`` renumbers them 0..k-1.
    Raises a structured :class:`MeshShrinkError` when the dead hosts'
    devices do not align to whole slots of any axis, or no viable
    smaller mesh exists.
    """
    from jax.sharding import Mesh

    names = list(mesh.axis_names)
    shape = tuple(int(s) for s in mesh.devices.shape)
    old_axes = dict(zip(names, shape))
    dead = sorted({int(h) for h in dead_hosts})
    if not dead:
        raise MeshShrinkError("no dead hosts to excise", axes=old_axes,
                              batch_axis=batch_axis)
    bad = [h for h in dead if not 0 <= h < topology.num_hosts]
    if bad:
        raise MeshShrinkError(
            f"dead host(s) {bad} out of range for "
            f"{topology.num_hosts}-host pod", axes=old_axes,
            dead_ranks=dead, batch_axis=batch_axis)
    dead_ordinals = frozenset(
        o for h in dead for o in topology.host_ordinals(h))
    batch_names = ((batch_axis,) if isinstance(batch_axis, str)
                   else tuple(batch_axis))
    order = [n for n in batch_names if n in names] + \
        [n for n in names if n not in batch_names]
    chosen = None
    for name in order:
        axis = names.index(name)
        slot_sets = _axis_slot_ordinals(shape, axis)
        lost = [s for s, members in enumerate(slot_sets)
                if members & dead_ordinals]
        covered = frozenset(o for s in lost for o in slot_sets[s])
        if covered == dead_ordinals and len(lost) < shape[axis]:
            chosen = (name, axis, lost)
            break
    if chosen is None:
        raise MeshShrinkError(
            f"dead host(s) {dead} (device ordinals "
            f"{sorted(dead_ordinals)}) do not align to whole slots of "
            f"any axis of mesh {old_axes}; the pod cannot excise them "
            "without re-tiling the survivors — restart the job on the "
            "remaining hosts instead", axes=old_axes, dead_ranks=dead,
            batch_axis=batch_names[0])
    name, axis, lost_slots = chosen
    slots = [s for s in range(shape[axis]) if s not in lost_slots]
    new_size = 1 << (len(slots).bit_length() - 1)
    if new_size >= shape[axis]:
        raise MeshShrinkError(
            f"'{name}' cannot shrink below its current size "
            f"{shape[axis]}", axes=old_axes, dead_ranks=dead,
            batch_axis=name)
    devices = np.take(mesh.devices, slots[:new_size], axis=axis)
    new_mesh = Mesh(devices, tuple(names))
    # hosts kept = hosts ALL of whose ordinals survive into the new mesh
    # (the power-of-two trim may drop additional live hosts' slots)
    id_to_ordinal = {id(d): i for i, d in enumerate(mesh.devices.flat)}
    kept_ordinals = {id_to_ordinal[id(d)] for d in devices.flat}
    kept_hosts = [h for h in topology.hosts()
                  if set(topology.host_ordinals(h)) <= kept_ordinals]
    if not kept_hosts:
        raise MeshShrinkError(
            f"no whole host survives the '{name}' shrink to {new_size} "
            "slot(s)", axes=old_axes, dead_ranks=dead, batch_axis=name)
    return new_mesh, topology.shrunk(kept_hosts), tuple(kept_hosts)


def default_mesh(n_devices=None):
    import jax

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return create_mesh({"dp": len(devs)}, devs)


def parse_mesh_spec(spec):
    """Parse a 'dp=2,fsdp=2,tp=-1' mesh-shape string into an ordered
    axis dict (a -1 size absorbs the remaining devices, create_mesh
    semantics). Axis names must come from AXES so a typo'd axis fails
    loudly instead of silently replicating."""
    axes = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh axis {part!r} in {spec!r}: want name=size")
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} in {spec!r}: want one of {AXES}")
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r} in {spec!r}")
        axes[name] = int(val)
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def named_mesh(spec=None, devices=None):
    """The named multi-axis training mesh (docs/parallel.md).

    ``spec`` is a 'dp=2,fsdp=2,tp=2' string, an axis dict, or None to
    read the ``MXNET_TPU_MESH_SHAPE`` env knob; with neither set this
    degrades to the pure data-parallel default_mesh so single-axis
    callers need no configuration. Axes with size 1 are kept — a
    dp=2,fsdp=1,tp=4 mesh still names all three axes so SpecLayout
    rules resolve uniformly.
    """
    if spec is None:
        spec = os.environ.get("MXNET_TPU_MESH_SHAPE", "").strip()
        if not spec:
            return default_mesh() if devices is None else create_mesh(
                {"dp": len(list(devices))}, devices)
    axes = spec if isinstance(spec, dict) else parse_mesh_spec(spec)
    return create_mesh(axes, devices)


def shard_map(fn, mesh, in_specs, out_specs, check=True):
    """Version-portable jax shard_map: jax >= 0.6 exposes `jax.shard_map`
    with the replication check named check_vma; earlier releases ship it
    as jax.experimental.shard_map with check_rep."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _smap

    return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=check)
