"""Functional optimizers for the sharded training step.

The imperative path (mxnet_tpu/optimizer/optimizer.py) mutates NDArray cells
via the fused update kernels (ops/optimizer_ops.py — the TPU analogue of the
reference's optimizer ops, src/operator/optimizer_op.cc). This module
re-exposes the SAME kernels as pure ``(w, g, state, t) -> (new_w, new_state)``
functions so the jitted mesh step can thread optimizer state functionally.
The step counter ``t`` is a traced int32 scalar (not baked at trace time), so
bias-corrected optimizers (adam/adamax/nadam/ftml/lamb) stay correct across
steps of one compiled executable.

Registry keyed by the same aliases as mx.optimizer.create.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["make_update_fn", "FUNCTIONAL_OPTIMIZERS"]

FUNCTIONAL_OPTIMIZERS = {}


def _register(*names):
    def deco(factory):
        for n in names:
            FUNCTIONAL_OPTIMIZERS[n] = factory
        return factory
    return deco


def _kernel(name):
    from ..ops.registry import get_op

    return get_op(name).fn


def _hyper(kw, default_lr):
    return {
        "lr": kw.pop("learning_rate", default_lr),
        "wd": kw.pop("wd", 0.0),
        "rescale_grad": kw.pop("rescale_grad", 1.0),
        "clip_gradient": kw.pop("clip_gradient", None),
    }


def _rescale_clip(g, rescale, clip):
    g = g * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return g


# Each factory(optimizer_params) returns (init_one, update_one):
#   init_one(name, w) -> per-param state pytree (tuples/arrays/()),
#   update_one(w, g, s, t) -> (new_w, new_s); t is a traced int32 step count.

@_register("sgd", "lbsgd")
def _sgd(kw):
    h = _hyper(kw, 0.01)
    momentum = kw.pop("momentum", 0.0)
    if momentum == 0.0:
        fn = _kernel("sgd_update")

        def update(w, g, s, t):
            return fn(w, g, **h)[0], ()
        return (lambda n, w: ()), update
    fn = _kernel("sgd_mom_update")

    def update(w, g, s, t):
        new_w, _, new_mom = fn(w, g, s, momentum=momentum, **h)
        return new_w, new_mom
    return (lambda n, w: jnp.zeros_like(w)), update


@_register("nag")
def _nag(kw):
    h = _hyper(kw, 0.01)
    momentum = kw.pop("momentum", 0.0)
    fn = _kernel("nag_mom_update")

    def update(w, g, s, t):
        new_w, _, new_mom = fn(w, g, s, momentum=momentum, **h)
        return new_w, new_mom
    return (lambda n, w: jnp.zeros_like(w)), update


@_register("adam")
def _adam(kw):
    h = _hyper(kw, 0.001)
    beta1 = kw.pop("beta1", 0.9)
    beta2 = kw.pop("beta2", 0.999)
    epsilon = kw.pop("epsilon", 1e-8)
    fn = _kernel("adam_update")
    base_lr = h.pop("lr")

    def update(w, g, s, t):
        m, v = s
        # bias correction folded into lr, with traced t (reference
        # optimizer.py Adam.update does this on the host per call)
        lr_t = base_lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        new_w, _, nm, nv = fn(w, g, m, v, lr=lr_t, beta1=beta1, beta2=beta2,
                              epsilon=epsilon, **h)
        return new_w, (nm, nv)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("adamw")
def _adamw(kw):
    h = _hyper(kw, 0.001)
    beta1 = kw.pop("beta1", 0.9)
    beta2 = kw.pop("beta2", 0.999)
    epsilon = kw.pop("epsilon", 1e-8)
    eta = kw.pop("eta", 1.0)
    fn = _kernel("adamw_update")

    def update(w, g, s, t):
        m, v = s
        new_w, _, nm, nv = fn(w, g, m, v, beta1=beta1, beta2=beta2,
                              epsilon=epsilon, eta=eta, **h)
        return new_w, (nm, nv)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("ftrl")
def _ftrl(kw):
    h = _hyper(kw, 0.1)
    lamda1 = kw.pop("lamda1", 0.01)
    beta = kw.pop("beta", 1.0)
    fn = _kernel("ftrl_update")

    def update(w, g, s, t):
        z, nacc = s
        new_w, _, nz, nn = fn(w, g, z, nacc, lamda1=lamda1, beta=beta, **h)
        return new_w, (nz, nn)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("rmsprop")
def _rmsprop(kw):
    h = _hyper(kw, 0.001)
    gamma1 = kw.pop("gamma1", 0.9)
    gamma2 = kw.pop("gamma2", 0.9)
    epsilon = kw.pop("epsilon", 1e-8)
    centered = kw.pop("centered", False)
    if not centered:
        fn = _kernel("rmsprop_update")

        def update(w, g, s, t):
            new_w, _, nn = fn(w, g, s, gamma1=gamma1, epsilon=epsilon, **h)
            return new_w, nn
        return (lambda n, w: jnp.zeros_like(w)), update
    fn = _kernel("rmspropalex_update")

    def update(w, g, s, t):
        nacc, gavg, delta = s
        new_w, _, nn, ng, nd = fn(w, g, nacc, gavg, delta, gamma1=gamma1,
                                  gamma2=gamma2, epsilon=epsilon, **h)
        return new_w, (nn, ng, nd)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w),
                          jnp.zeros_like(w))), update


@_register("adagrad")
def _adagrad(kw):
    h = _hyper(kw, 0.01)
    eps = kw.pop("eps", 1e-7)

    def update(w, g, s, t):
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        new_h = s + jnp.square(g)
        new_w = w - h["lr"] * g / (jnp.sqrt(new_h) + eps)
        return new_w, new_h
    return (lambda n, w: jnp.zeros_like(w)), update


@_register("adadelta")
def _adadelta(kw):
    h = _hyper(kw, 1.0)
    rho = kw.pop("rho", 0.9)
    epsilon = kw.pop("epsilon", 1e-5)

    def update(w, g, s, t):
        acc_g, acc_d = s
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
        delta = jnp.sqrt(acc_d + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
        new_acc_d = rho * acc_d + (1 - rho) * jnp.square(delta)
        return w - h["lr"] * delta, (new_acc_g, new_acc_d)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("adamax")
def _adamax(kw):
    h = _hyper(kw, 0.002)
    beta1 = kw.pop("beta1", 0.9)
    beta2 = kw.pop("beta2", 0.999)

    def update(w, g, s, t):
        m, u = s
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        nm = beta1 * m + (1 - beta1) * g
        nu = jnp.maximum(beta2 * u, jnp.abs(g))
        lr_t = h["lr"] / (1 - beta1 ** t)
        return w - lr_t * nm / (nu + 1e-8), (nm, nu)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("nadam")
def _nadam(kw):
    h = _hyper(kw, 0.001)
    beta1 = kw.pop("beta1", 0.9)
    beta2 = kw.pop("beta2", 0.999)
    epsilon = kw.pop("epsilon", 1e-8)
    schedule_decay = kw.pop("schedule_decay", 0.004)

    def momentum_t(t):
        return beta1 * (1 - 0.5 * 0.96 ** (t * schedule_decay))

    def update(w, g, s, t):
        m, v, m_sched = s
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        mt = momentum_t(t)
        mt1 = momentum_t(t + 1)
        new_sched = m_sched * mt
        g_prime = g / (1 - new_sched)
        nm = beta1 * m + (1 - beta1) * g
        nv = beta2 * v + (1 - beta2) * jnp.square(g)
        m_prime = nm / (1 - new_sched * mt1)
        v_prime = nv / (1 - beta2 ** t)
        m_bar = (1 - mt) * g_prime + mt1 * m_prime
        new_w = w - h["lr"] * m_bar / (jnp.sqrt(v_prime) + epsilon)
        return new_w, (nm, nv, new_sched)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w),
                          jnp.ones((), w.dtype))), update


@_register("ftml")
def _ftml(kw):
    h = _hyper(kw, 0.0025)
    beta1 = kw.pop("beta1", 0.6)
    beta2 = kw.pop("beta2", 0.999)
    epsilon = kw.pop("epsilon", 1e-8)

    def update(w, g, s, t):
        d, v, z = s
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        nv = beta2 * v + (1 - beta2) * jnp.square(g)
        d_t = (1 - beta1 ** t) / h["lr"] * (
            jnp.sqrt(nv / (1 - beta2 ** t)) + epsilon)
        sigma = d_t - beta1 * d
        nz = beta1 * z + (1 - beta1) * g - sigma * w
        return -nz / d_t, (d_t, nv, nz)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w),
                          jnp.zeros_like(w))), update


@_register("signum")
def _signum(kw):
    h = _hyper(kw, 0.01)
    momentum = kw.pop("momentum", 0.9)
    wd_lh = kw.pop("wd_lh", 0.0)
    if momentum == 0.0:
        fn = _kernel("signsgd_update")

        def update(w, g, s, t):
            return fn(w, g, **h)[0], ()
        return (lambda n, w: ()), update
    fn = _kernel("signum_update")

    def update(w, g, s, t):
        new_w, _, nm = fn(w, g, s, momentum=momentum, wd_lh=wd_lh, **h)
        return new_w, nm
    return (lambda n, w: jnp.zeros_like(w)), update


@_register("lamb")
def _lamb(kw):
    h = _hyper(kw, 0.001)
    beta1 = kw.pop("beta1", 0.9)
    beta2 = kw.pop("beta2", 0.999)
    epsilon = kw.pop("epsilon", 1e-6)
    lower_bound = kw.pop("lower_bound", -1.0)
    upper_bound = kw.pop("upper_bound", -1.0)
    bias_correction = kw.pop("bias_correction", True)
    p1 = _kernel("lamb_update_phase1")
    p2 = _kernel("lamb_update_phase2")
    lr = h.pop("lr")

    def update(w, g, s, t):
        m, v = s
        gu = p1(w, g, m, v, beta1=beta1, beta2=beta2, epsilon=epsilon, t=t,
                bias_correction=bias_correction, **h)
        nm = beta1 * m + (1 - beta1) * _rescale_clip(
            g, h["rescale_grad"], h["clip_gradient"])
        nv = beta2 * v + (1 - beta2) * jnp.square(_rescale_clip(
            g, h["rescale_grad"], h["clip_gradient"]))
        r1 = jnp.linalg.norm(w).reshape((1,))
        r2 = jnp.linalg.norm(gu).reshape((1,))
        new_w = p2(w, gu, r1, r2, lr=lr, lower_bound=lower_bound,
                   upper_bound=upper_bound)[0]
        return new_w, (nm, nv)
    return (lambda n, w: (jnp.zeros_like(w), jnp.zeros_like(w))), update


@_register("lars")
def _lars(kw):
    h = _hyper(kw, 0.1)
    momentum = kw.pop("momentum", 0.9)
    eta = kw.pop("eta", 0.001)
    epsilon = kw.pop("epsilon", 1e-8)

    def update(w, g, s, t):
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            eta * w_norm / (g_norm + h["wd"] * w_norm + epsilon), 1.0)
        lr_layer = h["lr"] * trust
        new_mom = momentum * s + lr_layer * (g + h["wd"] * w)
        return w - new_mom, new_mom
    return (lambda n, w: jnp.zeros_like(w)), update


@_register("dcasgd")
def _dcasgd(kw):
    h = _hyper(kw, 0.1)
    momentum = kw.pop("momentum", 0.0)
    lamda = kw.pop("lamda", 0.04)

    def update(w, g, s, t):
        mom, prev_w = s
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        comp = g + lamda * g * g * (w - prev_w)
        new_mom = momentum * mom - h["lr"] * comp
        new_w = w + new_mom
        return new_w, (new_mom, new_w)
    return (lambda n, w: (jnp.zeros_like(w), jnp.array(w))), update


@_register("sgld")
def _sgld(kw):
    h = _hyper(kw, 0.01)

    def init(name, w):
        # per-param langevin noise stream; deterministic in the param name
        seed = abs(hash(name)) % (2 ** 31 - 1)
        return jax.random.PRNGKey(seed)

    def update(w, g, s, t):
        key, sub = jax.random.split(s)
        g = _rescale_clip(g, h["rescale_grad"], h["clip_gradient"])
        g = g + h["wd"] * w
        noise = jax.random.normal(sub, w.shape, w.dtype) * jnp.sqrt(h["lr"])
        return w - 0.5 * h["lr"] * g + noise, key
    return init, update


def make_update_fn(optimizer="sgd", optimizer_params=None):
    """Build ``(init, update)`` for a whole param dict.

    init(params) -> opt_state (includes the traced step counter)
    update(params, grads, opt_state) -> (new_params, new_opt_state)
    """
    factory = FUNCTIONAL_OPTIMIZERS.get(optimizer)
    if factory is None:
        raise ValueError(
            f"unsupported sharded optimizer '{optimizer}'; functional "
            f"registry has: {sorted(FUNCTIONAL_OPTIMIZERS)}")
    init_one, update_one = factory(dict(optimizer_params or {}))

    def init(params):
        return {"t": jnp.zeros((), jnp.int32),
                "state": {k: init_one(k, v) for k, v in params.items()}}

    def update(params, grads, opt_state):
        t = opt_state["t"] + 1
        new_p, new_s = {}, {}
        for k in params:
            new_p[k], new_s[k] = update_one(
                params[k], grads[k], opt_state["state"][k], t)
        return new_p, {"t": t, "state": new_s}

    return init, update
