"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

The long-context capability the north star calls for (absent in the
reference, whose longest-sequence tool is BucketingModule — SURVEY.md
§2.3): the sequence axis is sharded over the mesh, each device holds one
block of Q/K/V, and K/V blocks rotate around the ring via
`lax.ppermute` while each device accumulates its queries' attention with
a numerically-stable online (flash-style) softmax. Peak memory per device
is O(T_local^2) instead of O(T^2), compute overlaps with the ICI
transfers, and the whole thing is one jitted SPMD program —
reverse-mode AD through the loop comes from jax for free.

Usage (global arrays, T sharded over 'sp')::

    mesh = parallel.create_mesh({"sp": 8})
    out = parallel.ring.ring_attention(q, k, v, mesh=mesh, causal=True)

`ring_attention_inner` is the raw per-shard function for embedding inside
a larger shard_map'd training step.
"""
from __future__ import annotations

import functools

import numpy as _np

__all__ = ["ring_attention", "ring_attention_inner", "attention"]

_NEG = -1e30


def ring_attention_inner(q, k, v, axis_name="sp", causal=False, scale=None,
                         impl="dense", interpret=False):
    """Per-shard ring attention body (call inside shard_map).

    q, k, v: (B, H, T_local, D) — this device's sequence block. Returns
    (B, H, T_local, D) attention output for the local queries over the
    GLOBAL sequence.

    impl='dense' materializes the per-hop (T_local, T_local) score block;
    impl='flash' runs each hop through the Pallas streaming kernel
    (ops/pallas_kernels.py) with global positional offsets, dropping
    per-device attention memory from O(T_local²) to O(T_local·BLOCK_K) —
    the two kernels composed. Hop results merge by log-sum-exp, and the
    kernel's custom_vjp carries the lse cotangent, so reverse-mode AD
    through the ring works for both implementations.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, h, t, d = q.shape
    s_scale = scale if scale is not None else 1.0 / _np.sqrt(d)
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32)
    # derive the accumulators from q so they inherit its full
    # varying-manual-axes type (dp, sp, ...) — fresh constants would make
    # the fori_loop carry type diverge from the rotating K/V blocks
    m0 = q32[..., :1] * 0 + _NEG
    l0 = q32[..., :1] * 0
    o0 = q32 * 0
    qpos = my_idx * t + jnp.arange(t)

    if impl == "flash":
        from ..ops.pallas_kernels import flash_attention_with_lse

        def body(i, carry):
            m, w, o, kc, vc = carry
            # axis_index must be (re)taken INSIDE the loop body: a value
            # closed over from outside becomes a while-body constant, and
            # under check_vma/check_rep=False jax re-materializes it as a
            # PartitionId HLO, which SPMD partitioning rejects
            # ("UNIMPLEMENTED: PartitionId instruction is not supported").
            my = lax.axis_index(axis_name)
            src = (my - i) % axis_size
            # per-hop streaming kernel: normalized block output + its lse
            out_i, lse_i = flash_attention_with_lse(
                q, kc, vc, causal=causal, scale=s_scale,
                interpret=interpret, q_offset=my * t,
                k_offset=src * t)
            # merge normalized hop results by log-sum-exp weight
            lse32 = lse_i.astype(jnp.float32)
            m_new = jnp.maximum(m, lse32)
            corr = jnp.exp(m - m_new)
            wi = jnp.exp(lse32 - m_new)
            o_new = o * corr + wi * out_i.astype(jnp.float32)
            w_new = w * corr + wi
            perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return m_new, w_new, o_new, kc, vc

        m, w, o, _, _ = lax.fori_loop(0, axis_size, body, (m0, l0, o0, k, v))
        return (o / jnp.maximum(w, 1e-20)).astype(q.dtype)

    def body(i, carry):
        m, l, o, kc, vc = carry
        # the K/V block currently held arrived from device (my_idx - i)
        src = (my_idx - i) % axis_size
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            kc.astype(jnp.float32)) * s_scale
        if causal:
            kpos = src * t + jnp.arange(t)
            mask = qpos[:, None] >= kpos[None, :]
            logits = jnp.where(mask, logits, _NEG)
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p,
                                      vc.astype(jnp.float32))
        # rotate K/V one hop around the ring (overlaps with next block's
        # compute under XLA's async collectives)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, o_new, kc, vc

    m, l, o, _, _ = lax.fori_loop(0, axis_size, body, (m0, l0, o0, k, v))
    return (o / jnp.maximum(l, 1e-20)).astype(q.dtype)


@functools.lru_cache(maxsize=64)
def _ring_fn(mesh, axis_name, causal, scale, impl, interpret,
             sched_tag=""):
    """One jitted SPMD program per config — re-built closures would defeat
    jax.jit's identity-keyed cache and recompile on every call.
    ``sched_tag`` is the schedule-table digest (tune.table_digest()): the
    per-hop flash kernel resolves its blocks from the table at trace
    time, so a table change must re-key this cache instead of serving a
    program built under the old schedule."""
    import jax
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    inner = functools.partial(ring_attention_inner, axis_name=axis_name,
                              causal=causal, scale=scale, impl=impl,
                              interpret=interpret)
    from .mesh import shard_map

    # pallas_call outputs carry no varying-mesh-axes (vma) annotation, so
    # the flash path runs with the replication/vma type check off
    return jax.jit(shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec, check=(impl != "flash")))


def _pick_impl(impl, t_local, d, ring=True):
    from ..ops.pallas_kernels import pallas_available
    from ..tune import schedule as _tune_schedule

    if impl != "auto":
        return impl, False
    if not _tune_schedule.flash_shape_supported(t_local, d):
        return "dense", False
    if pallas_available():
        return "flash", False
    # CPU hosts: Pallas interpret mode is emulation-slow; for ring hops
    # it is still the only way past a huge per-hop dense block, but the
    # single-device path should keep XLA's fast dense composition
    if ring and t_local >= 4096:
        return "flash", True
    return "dense", False


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=False,
                   scale=None, impl="auto", interpret=False):
    """Sequence-parallel attention over global arrays.

    q, k, v: (B, H, T, D) NDArrays or jax arrays with T divisible by the
    mesh's `axis_name` size. The sequence axis is sharded over the ring;
    output has the same global shape/sharding.

    impl: 'dense' | 'flash' | 'auto'. 'flash' streams each hop through
    the Pallas kernel (O(T_local·BLOCK_K) memory per device); 'auto'
    picks flash on TPU when shapes allow, dense otherwise.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import create_mesh

    if mesh is None:
        mesh = create_mesh({axis_name: len(jax.devices())})
    if axis_name not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} has no {axis_name!r} "
                         "axis; build it with parallel.create_mesh("
                         f"{{'{axis_name}': n}})")
    raw = [a._data if hasattr(a, "_data") else jnp.asarray(a)
           for a in (q, k, v)]
    t = raw[0].shape[2]
    n = mesh.shape[axis_name]
    if t % n != 0:
        raise ValueError(f"sequence length {t} not divisible by "
                         f"{axis_name} size {n}")
    chosen, auto_interp = _pick_impl(impl, t // n, raw[0].shape[3])
    interpret = interpret or auto_interp
    spec = P(None, None, axis_name, None)
    from ..tune import schedule as _tune_schedule

    # fingerprint_token (not table_digest): the MXNET_TPU_AUTOTUNE kill
    # switch collapses the token to '' exactly like the AOT cache key,
    # so flipping it re-keys the cached jitted program too
    fn = _ring_fn(mesh, axis_name, causal, scale, chosen, bool(interpret),
                  _tune_schedule.fingerprint_token()
                  if chosen == "flash" else "")
    arrs = [jax.device_put(a, NamedSharding(mesh, spec)) for a in raw]
    out = fn(*arrs)
    if hasattr(q, "_data"):
        from ..ndarray.ndarray import NDArray

        return NDArray(out, getattr(q, "_ctx", None))
    return out


def attention(q, k, v, causal=False, scale=None, mesh=None,
              axis_name="sp", impl="auto", interpret=False):
    """Unified attention entry: picks dense / flash / ring by shape+mesh.

    - a mesh with an `axis_name` axis of size > 1 -> ring attention
      (sequence parallel; per-hop kernel chosen by `impl`)
    - single device, flash-compatible shape on TPU -> Pallas flash kernel
    - otherwise -> the fused XLA dense composition
      (ops/nn.py scaled_dot_product_attention)
    """
    import jax.numpy as jnp

    if mesh is not None and mesh.shape.get(axis_name, 1) > 1:
        return ring_attention(q, k, v, mesh=mesh, axis_name=axis_name,
                              causal=causal, scale=scale, impl=impl,
                              interpret=interpret)
    raw_q = q._data if hasattr(q, "_data") else jnp.asarray(q)
    b, h, t, d = raw_q.shape
    chosen, auto_interp = _pick_impl(impl, t, d, ring=False)
    if chosen == "flash":
        from ..ops.pallas_kernels import flash_attention_with_grad

        return flash_attention_with_grad(
            q, k, v, causal=causal, scale=scale,
            interpret=interpret or auto_interp)
    if hasattr(q, "_data"):
        from .. import ndarray as nd

        return nd.scaled_dot_product_attention(q, k, v, causal=causal,
                                               scale=scale)
    from ..ops.nn import _sdpa

    return _sdpa(raw_q, jnp.asarray(k), jnp.asarray(v), causal=causal,
                 scale=scale)
