"""SpecLayout — per-parameter PartitionSpec assignment for transformer
blocks on a named dp×fsdp×tp mesh (docs/parallel.md).

The canonical data/fsdp/tensor layout (scaling-book style, SNIPPETS.md
[3]) adapted to gluon's Dense weight convention ``W: (units_out,
in_units)`` with ``y = x @ W.T``:

- QKV / FFN-up projections are COLUMN-parallel: the output features
  split over ``tp`` (each tp shard computes a head/neuron slice, no
  collective needed on the way in), so gluon's (out, in) weight is
  ``P(tp, fsdp)``.
- attention-output / FFN-down projections are ROW-parallel: the input
  features arrive tp-sharded from the column-parallel producer, so the
  contraction dim splits over ``tp`` and XLA inserts the one
  all-reduce per block: ``P(fsdp, tp)``.
- embedding and LM-head tables shard their vocab rows over the whole
  non-data parameter surface ``(fsdp, tp)`` — the biggest tables get
  the most shards.
- everything else (norm scales, small biases) stays replicated; the
  column-parallel biases follow their weight's output split (``tp``).

ShardedTrainer consumes this as ``param_rules`` — an ordered
``(regex, PartitionSpec)`` list, first match wins, unmatched params
replicate — so SpecLayout is pure data: no model surgery, and the same
rules drive the captured and uncaptured step identically.
"""
from __future__ import annotations

__all__ = ["SpecLayout"]


class SpecLayout:
    """Assigns PartitionSpecs to gluon transformer parameters.

    ``data_axis``/``fsdp_axis``/``tp_axis`` name the mesh axes; pass
    None (or use :meth:`for_mesh`) to drop an axis the mesh doesn't
    have — the layout then degrades gracefully (dp-only meshes get pure
    data parallelism with replicated params, dp×tp meshes get tensor
    parallelism without parameter sharding, and so on).
    """

    def __init__(self, data_axis="dp", fsdp_axis="fsdp", tp_axis="tp"):
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis

    @classmethod
    def for_mesh(cls, mesh, data_axis="dp", fsdp_axis="fsdp",
                 tp_axis="tp"):
        """A SpecLayout with every axis the mesh lacks dropped to None."""
        names = set(mesh.axis_names)
        return cls(data_axis=data_axis if data_axis in names else None,
                   fsdp_axis=fsdp_axis if fsdp_axis in names else None,
                   tp_axis=tp_axis if tp_axis in names else None)

    # ----------------------------------------------------------- specs
    def _spec(self, *dims):
        """Build a PartitionSpec, collapsing dropped axes to None."""
        from jax.sharding import PartitionSpec as P

        out = []
        for d in dims:
            if isinstance(d, tuple):
                kept = tuple(a for a in d if a is not None)
                out.append(kept if kept else None)
            else:
                out.append(d)
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def qkv_projection(self):
        """(3·units, units) column-parallel: heads split over tp."""
        return self._spec(self.tp_axis, self.fsdp_axis)

    def attn_output(self):
        """(units, units) row-parallel: contraction dim over tp."""
        return self._spec(self.fsdp_axis, self.tp_axis)

    def ffn_up(self):
        """(4·units, units) column-parallel."""
        return self._spec(self.tp_axis, self.fsdp_axis)

    def ffn_down(self):
        """(units, 4·units) row-parallel."""
        return self._spec(self.fsdp_axis, self.tp_axis)

    def embedding(self):
        """(vocab, units) vocab rows over the full parameter surface."""
        return self._spec((self.fsdp_axis, self.tp_axis), None)

    def lm_head(self):
        """(vocab, units) — same table shape as the embedding."""
        return self._spec((self.fsdp_axis, self.tp_axis), None)

    def column_bias(self):
        """Bias of a column-parallel projection follows its out split."""
        return self._spec(self.tp_axis)

    def replicated(self):
        return self._spec()

    # ------------------------------------------------------ rule table
    def param_rules(self):
        """Ordered (regex, PartitionSpec) rules for ShardedTrainer.

        Written against the model_zoo transformer's stable param
        suffixes (gluon prefixes: ``attn_qkv_``/``attn_out_`` inside
        MultiHeadAttention, ``ff1_``/``ff2_`` for the MLP,
        ``embed_``/``head_`` for the tables); first match wins and
        anything unmatched — norms, positional table, small biases —
        replicates, which is exactly the layout's intent.
        """
        return (
            (r".*attn_qkv_weight$", self.qkv_projection()),
            (r".*attn_qkv_bias$", self.column_bias()),
            (r".*attn_out_weight$", self.attn_output()),
            (r".*ff1_weight$", self.ffn_up()),
            (r".*ff1_bias$", self.column_bias()),
            (r".*ff2_weight$", self.ffn_down()),
            (r".*embed_weight$", self.embedding()),
            (r".*head_weight$", self.lm_head()),
        )

    # ------------------------------------------------------ batch side
    def batch_axes(self):
        """Mesh axes the batch dim shards over: dp and (flat-data) fsdp."""
        return tuple(a for a in (self.data_axis, self.fsdp_axis)
                     if a is not None)

    def batch_spec(self):
        """PartitionSpec for (B, ...) batches: dim 0 over dp×fsdp."""
        return self._spec(self.batch_axes())

    def __repr__(self):
        return (f"SpecLayout(data={self.data_axis!r}, "
                f"fsdp={self.fsdp_axis!r}, tp={self.tp_axis!r})")
