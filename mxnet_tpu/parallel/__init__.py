"""Distributed/parallel execution over TPU meshes.

SURVEY.md §2.3: the reference's parallelism stack (Comm/NCCL/ps-lite +
DataParallelExecutorGroup) is replaced by named device meshes + GSPMD
shardings; tp/pp/sp axes — absent in the reference — are exposed here as
first-class (free on XLA).
"""
from .mesh import (create_mesh, default_mesh, named_mesh, local_devices,
                   AXES, shard_map, PodTopology, pod_mesh,
                   shrink_mesh_hosts)
from .functional import functional_call, param_arrays, aux_arrays
from .layout import SpecLayout
from .trainer import ShardedTrainer, make_update_fn
from . import mesh
from . import functional
from . import layout
from . import trainer


def __getattr__(name):
    import importlib

    if name in ("ring", "ring_attention", "attention"):
        mod = importlib.import_module(".ring_attention", __name__)
        globals()["ring"] = mod
        globals()["ring_attention"] = mod.ring_attention
        globals()["attention"] = mod.attention
        return globals()[name]
    raise AttributeError(name)
