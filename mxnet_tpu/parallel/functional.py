"""Gluon net -> pure jax function bridge.

The TPU-native counterpart of the reference's executor bind: a Block's
imperative forward is re-run with its parameter cells temporarily rebound to
tracers, producing a pure ``(params, inputs) -> outputs`` function that
jax.jit / pjit can compile and shard. This is the same mutation->functional
discipline as mxnet_tpu.jit (SURVEY.md §7 hard part 2), packaged for the
distributed path.
"""
from __future__ import annotations

from ..ndarray.ndarray import NDArray

__all__ = ["functional_call", "param_arrays", "aux_arrays", "RNG_KEY"]

# Reserved aux-dict entry threading the global PRNG key through the pure
# function: stochastic ops (Dropout) split it per call, and the advanced key
# rides back out in new_aux — so repeated jitted steps draw fresh masks
# instead of baking one key in as a compile-time constant.
RNG_KEY = "__rng_key__"


def _split_params(net):
    params, aux = {}, {}
    for name, p in net.collect_params().items():
        (params if p.grad_req != "null" else aux)[name] = p
    return params, aux


def param_arrays(net):
    """Trainable parameter values as a {name: jax.Array} dict."""
    return {k: p.data().data_ for k, p in _split_params(net)[0].items()}


def aux_arrays(net):
    """Auxiliary state (BatchNorm running stats, RNG key, ...) as
    {name: jax.Array}. Includes the threaded PRNG key under ``RNG_KEY``."""
    from .. import random as _random

    out = {k: p.data().data_ for k, p in _split_params(net)[1].items()}
    out[RNG_KEY] = _random.generator_key().data_
    return out


def functional_call(net, train=False):
    """Returns ``fn(params, aux, *inputs) -> (outputs, new_aux)`` — a pure,
    jittable view of ``net``.

    ``params``/``aux`` are {name: array} dicts matching param_arrays /
    aux_arrays. In train mode, mutated aux state (running stats) is returned
    as ``new_aux``; in eval mode new_aux == aux.
    """
    from .. import autograd
    from .. import random as _random
    from ..jit import TraceSession

    params, aux = _split_params(net)
    cells = {name: p.data() for name, p in {**params, **aux}.items()}
    key_cell = _random.generator_key()

    def fn(pvals, avals, *inputs):
        saved = {n: c._data for n, c in cells.items()}
        saved_key = key_cell._data
        vals = {**pvals, **avals}
        try:
            for n, c in cells.items():
                if n in vals:
                    c._data = vals[n]
            if RNG_KEY in avals:
                key_cell._data = avals[RNG_KEY]
            in_nds = [NDArray(x) for x in inputs]
            with TraceSession() as sess:
                for a in in_nds:
                    sess.note_created(a)
                with autograd.pause(train_mode=train):
                    out = net(*in_nds)
            outs = [o.data_ for o in (out if isinstance(out, (list, tuple))
                                      else [out])]
            new_aux = {n: cells[n]._data for n in avals if n != RNG_KEY}
            if RNG_KEY in avals:
                new_aux[RNG_KEY] = key_cell._data
        finally:
            for n, c in cells.items():
                c._data = saved[n]
            key_cell._data = saved_key
        return (outs[0] if len(outs) == 1 else tuple(outs)), new_aux

    return fn
