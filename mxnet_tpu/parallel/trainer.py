"""Sharded training step over a device mesh.

The TPU-native replacement for DataParallelExecutorGroup + kvstore push/pull
(SURVEY.md §2.3): one jitted step function holds forward, backward, gradient
allreduce, and optimizer update. Parameters/batches carry NamedShardings on
the mesh; the gradient reduction over the 'dp' axis is inserted by XLA
(GSPMD) because the loss is a mean over the globally-sharded batch — the
explicit-NCCL push/pull of the reference collapses into compiler-placed ICI
collectives. Tensor-parallel shardings are expressed as parameter
PartitionSpec rules.
"""
from __future__ import annotations

import re

from .functional import functional_call, param_arrays, aux_arrays
from .mesh import create_mesh

__all__ = ["ShardedTrainer", "sgd_init", "make_update_fn"]


def _tree_map(f, *trees):
    return {k: f(*(t[k] for t in trees)) for k in trees[0]}


def sgd_init(params):
    return {k: None for k in params}


def make_update_fn(optimizer="sgd", optimizer_params=None):
    """Functional optimizer update built from the registered fused update
    ops (ops/optimizer_ops.py — same kernels the imperative path uses)."""
    import jax.numpy as jnp

    from ..ops.registry import get_op

    kw = dict(optimizer_params or {})
    lr = kw.pop("learning_rate", 0.01)
    wd = kw.pop("wd", 0.0)
    momentum = kw.pop("momentum", 0.0)
    rescale = kw.pop("rescale_grad", 1.0)
    clip = kw.pop("clip_gradient", None)

    if optimizer == "sgd" and momentum == 0.0:
        fn = get_op("sgd_update").fn

        def init(params):
            return {k: () for k in params}

        def update(w, g, s):
            new_w = fn(w, g, lr=lr, wd=wd, rescale_grad=rescale,
                       clip_gradient=clip)[0]
            return new_w, ()
    elif optimizer == "sgd":
        fn = get_op("sgd_mom_update").fn

        def init(params):
            return {k: jnp.zeros_like(v) for k, v in params.items()}

        def update(w, g, s):
            new_w, _, new_mom = fn(w, g, s, lr=lr, momentum=momentum, wd=wd,
                                   rescale_grad=rescale, clip_gradient=clip)
            return new_w, new_mom
    elif optimizer == "adam":
        fn = get_op("adam_update").fn
        beta1 = kw.pop("beta1", 0.9)
        beta2 = kw.pop("beta2", 0.999)
        epsilon = kw.pop("epsilon", 1e-8)

        def init(params):
            return {k: (jnp.zeros_like(v), jnp.zeros_like(v))
                    for k, v in params.items()}

        def update(w, g, s):
            m, v = s
            new_w, _, new_m, new_v = fn(w, g, m, v, lr=lr, beta1=beta1,
                                        beta2=beta2, epsilon=epsilon, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip)
            return new_w, (new_m, new_v)
    else:
        raise ValueError(f"unsupported sharded optimizer '{optimizer}' "
                         "(sgd / adam; extend make_update_fn)")
    return init, update


class ShardedTrainer:
    """Compiles a full training step over a mesh.

    Parameters
    ----------
    net : initialized gluon Block (params already materialized)
    loss_fn : gluon Loss or callable(pred_nd, label_nd)->NDArray
    optimizer, optimizer_params : like gluon.Trainer
    mesh : jax.sharding.Mesh (default: all-devices 'dp' mesh)
    param_rules : list of (regex, PartitionSpec) — first match wins;
        unmatched params are replicated. This is where tp/pp/ep shardings
        plug in.
    batch_axis_name : mesh axis the batch dimension is sharded over.
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, param_rules=(), batch_axis_name="dp"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.net = net
        self.mesh = mesh if mesh is not None else create_mesh()
        self.loss_fn = loss_fn
        self._fwd = functional_call(net, train=True)
        self.params = param_arrays(net)
        self.aux = aux_arrays(net)
        init, update = make_update_fn(optimizer, optimizer_params)
        self.opt_state = init(self.params)
        self._update = update
        self._rules = [(re.compile(pat), spec) for pat, spec in param_rules]
        self._batch_axis = batch_axis_name

        def spec_for(name):
            for pat, spec in self._rules:
                if pat.match(name):
                    return spec
            return P()

        self._param_sharding = {
            k: NamedSharding(self.mesh, spec_for(k)) for k in self.params}
        repl = NamedSharding(self.mesh, P())
        self._aux_sharding = {k: repl for k in self.aux}
        self._batch_sharding = NamedSharding(self.mesh, P(batch_axis_name))
        self._place()
        self._step = None

    def _place(self):
        import jax

        self.params = {k: jax.device_put(v, self._param_sharding[k])
                       for k, v in self.params.items()}
        self.aux = {k: jax.device_put(v, self._aux_sharding[k])
                    for k, v in self.aux.items()}
        self.opt_state = jax.tree.map(
            lambda v: jax.device_put(v, jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec())), self.opt_state)

    def _build_step(self):
        import jax

        fwd = self._fwd
        loss_fn = self.loss_fn
        update = self._update

        from ..ndarray.ndarray import NDArray
        from ..jit import TraceSession

        def compute_loss(params, aux, x, y):
            out, new_aux = fwd(params, aux, x)
            with TraceSession() as sess:
                out_nd, y_nd = NDArray(out), NDArray(y)
                sess.note_created(out_nd)
                sess.note_created(y_nd)
                loss = loss_fn(out_nd, y_nd)
            return loss.data_.mean(), new_aux

        def step(params, aux, opt_state, x, y):
            (loss, new_aux), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params, aux, x, y)
            new_params, new_opt = {}, {}
            for k in params:
                new_params[k], new_opt[k] = update(
                    params[k], grads[k], opt_state[k])
            return new_params, new_aux, new_opt, loss

        out_shardings = (self._param_sharding, self._aux_sharding,
                         None, None)
        self._step = jax.jit(
            step,
            in_shardings=(self._param_sharding, self._aux_sharding, None,
                          self._batch_sharding, self._batch_sharding),
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2))

    def step(self, x, y):
        """Run one sharded training step; returns the scalar loss."""
        import jax

        from ..ndarray.ndarray import NDArray

        if self._step is None:
            self._build_step()
        if isinstance(x, NDArray):
            x = x.data_
        if isinstance(y, NDArray):
            y = y.data_
        x = jax.device_put(x, self._batch_sharding)
        y = jax.device_put(y, self._batch_sharding)
        self.params, self.aux, self.opt_state, loss = self._step(
            self.params, self.aux, self.opt_state, x, y)
        return loss

    def sync_to_net(self):
        """Write the sharded parameter state back into the gluon net."""
        for name, p in self.net.collect_params().items():
            if name in self.params:
                p.data()._set_data(self.params[name])
            elif name in self.aux:
                p.data()._set_data(self.aux[name])
