"""Sharded training step over a device mesh.

The TPU-native replacement for DataParallelExecutorGroup + kvstore push/pull
(SURVEY.md §2.3): one jitted step function holds forward, backward, gradient
allreduce, and optimizer update. Parameters/batches carry NamedShardings on
the mesh; the gradient reduction over the 'dp' axis is inserted by XLA
(GSPMD) because the loss is a mean over the globally-sharded batch — the
explicit-NCCL push/pull of the reference collapses into compiler-placed ICI
collectives. Tensor-parallel shardings are expressed as parameter
PartitionSpec rules.
"""
from __future__ import annotations

import re

from ..observability import trace as _obs_trace
from .functional import functional_call, param_arrays, aux_arrays, RNG_KEY
from .mesh import create_mesh
from .optim import make_update_fn

__all__ = ["ShardedTrainer", "make_update_fn"]


class ShardedTrainer:
    """Compiles a full training step over a mesh.

    Parameters
    ----------
    net : initialized gluon Block (params already materialized)
    loss_fn : gluon Loss or callable(pred_nd, label_nd)->NDArray
    optimizer, optimizer_params : like gluon.Trainer
    mesh : jax.sharding.Mesh (default: all-devices 'dp' mesh)
    param_rules : list of (regex, PartitionSpec) — first match wins;
        unmatched params are replicated. This is where tp/pp/ep shardings
        plug in.
    batch_axis_name : mesh axis the batch dimension is sharded over.
    dtype : compute dtype policy. None = model dtype (fp32). 'bfloat16'
        (or 'float16') casts params/activations for forward+backward —
        fp32 master weights and optimizer state, bf16 MXU math — the TPU
        counterpart of the reference's AMP (contrib/amp/amp.py:251).
    checkpoint_manager : resilience.CheckpointManager, optional — arms
        the elastic mesh-shrink resume: a PeerLostError raised inside
        ``step`` is survived by rebuilding a smaller mesh from the
        surviving ranks and reloading the latest reshardable checkpoint
        onto it (docs/resilience.md). Without one, a dead peer stays
        terminal. ``enable_recovery`` attaches it after construction.
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, param_rules=(), batch_axis_name="dp",
                 dtype=None, remat=None, checkpoint_manager=None):
        import jax

        from ..remat import mirror_enabled, resolve_policy

        self.net = net
        self.loss_fn = loss_fn
        self._fwd = functional_call(net, train=True)
        # remat: False disables, None follows MXNET_BACKWARD_DO_MIRROR,
        # True/str/callable select a jax.checkpoint policy (remat.py) —
        # the backward then recomputes non-saved activations, trading
        # FLOPs for peak HBM (reference gradient mirroring)
        if remat is None:
            remat = mirror_enabled()
        if remat:
            self._fwd = jax.checkpoint(
                self._fwd, policy=resolve_policy(remat))
        self.params = param_arrays(net)
        self.aux = aux_arrays(net)
        self._compute_dtype = dtype
        self._optimizer = optimizer
        self._optimizer_params = dict(optimizer_params or {})
        init, update = make_update_fn(optimizer, dict(self._optimizer_params))
        self.opt_state = init(self.params)
        self._update = update
        self._rules = [(re.compile(pat), spec) for pat, spec in param_rules]
        # one mesh axis name, or a tuple of names when the batch dim is
        # sharded over several (dp×fsdp — SpecLayout.batch_axes())
        self._batch_axis = (batch_axis_name if isinstance(batch_axis_name,
                                                          str)
                            else tuple(batch_axis_name))
        # elastic recovery (resilience.elastic): the manager the
        # mesh-shrink resume reloads state from on PeerLostError; without
        # one, a dead peer stays terminal (enable_recovery attaches late)
        self._ckpt_mgr = checkpoint_manager
        self.last_recovery = None
        # pod topology (parallel.mesh.PodTopology): set by bind_pod/
        # for_pod when the mesh spans host failure domains; None means
        # rank-level elastic recovery only
        self._pod = None
        self._bind_mesh(mesh if mesh is not None else create_mesh())
        self._place()
        # elastic execution state (resilience.elastic): current sticky
        # accumulation count and a monotonically increasing step counter
        # for crash reports (the executables live in _bind_mesh state)
        self._elastic_n = 1
        self._step_count = 0
        # SDC defense (resilience.integrity): the last step's in-graph
        # fingerprint output (lazy — host-read only on access) and the
        # SIGTERM preemption trap (finish the step, checkpoint, drain)
        self._last_fp_out = None
        from ..resilience import integrity as _integrity

        _integrity.install_preempt_handler()

    def _spec_for(self, name):
        from jax.sharding import PartitionSpec as P

        for pat, spec in self._rules:
            if pat.match(name):
                return spec
        return P()

    def _batch_axis_names(self):
        """The batch axes as a tuple (a single name normalizes)."""
        ba = self._batch_axis
        return (ba,) if isinstance(ba, str) else tuple(ba)

    def _batch_shards(self):
        """How many ways the batch dim splits on the CURRENT mesh: the
        product of the batch axes' extents (dp alone, or dp×fsdp when
        the batch is sharded over both)."""
        import math

        return math.prod(int(self.mesh.shape.get(a, 1))
                         for a in self._batch_axis_names())

    def _bind_mesh(self, mesh):
        """(Re)derive every mesh-dependent binding — NamedShardings for
        params/aux/batch, the multi-process flag, and the compiled step/
        elastic executables (invalidated: they bake the old mesh in).
        Used at construction and by the peer-loss mesh-shrink resume;
        does NOT move any arrays (placement is _place or a restore)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self._param_sharding = {
            k: NamedSharding(mesh, self._spec_for(k)) for k in self.params}
        repl = NamedSharding(mesh, P())
        self._aux_sharding = {k: repl for k in self.aux}
        self._batch_sharding = NamedSharding(mesh, P(self._batch_axis))
        self._multiproc = self._is_multiprocess()
        self._step = None
        self._step_masked = None
        self._grads_fn = None
        self._apply_fn = None

    def enable_recovery(self, checkpoint_manager):
        """Attach the CheckpointManager the elastic mesh-shrink resume
        reloads state from when a peer dies (docs/resilience.md). The
        manager should already hold (or be about to receive) reshardable
        v2 checkpoints of THIS trainer. Returns self for chaining."""
        self._ckpt_mgr = checkpoint_manager
        return self

    def _place(self):
        import numpy as np

        import jax
        import jax.numpy as jnp

        multiproc = self._multiproc
        if multiproc:
            # Host values must first be made CONSISTENT across processes:
            # each worker initializes from its own random stream, and
            # divergent "replicated" buffers silently train divergent
            # models (losses still agree — each rank's contribution enters
            # the same psum — but the weights drift apart; caught by the
            # dryrun's bitwise cross-rank check). The reference's dist
            # kvstore init broadcasts rank-0 values (kvstore_dist.h Init);
            # ONE pytree-level broadcast covers params+aux+opt_state
            # instead of one collective per leaf.
            from jax.experimental import multihost_utils

            host_tree = jax.tree.map(
                np.asarray, (self.params, self.aux, self.opt_state))
            self.params, self.aux, self.opt_state = \
                multihost_utils.broadcast_one_to_all(host_tree)

        def put(v, sharding):
            if multiproc:
                # every process now holds identical full host values; build
                # each local shard directly — device_put would attempt a
                # cross-host transfer
                arr = np.asarray(v)
                return jax.make_array_from_callback(
                    arr.shape, sharding, lambda idx: arr[idx])
            # device_put may alias the input buffer when placement already
            # matches; always copy so step donation never deletes a buffer
            # the net (or another trainer) still references. Init-only cost.
            return jax.device_put(jnp.array(v, copy=True), sharding)

        self.params = {k: put(v, self._param_sharding[k])
                       for k, v in self.params.items()}
        self.aux = {k: put(v, self._aux_sharding[k])
                    for k, v in self.aux.items()}
        self.opt_state = jax.tree.map(put, self.opt_state,
                                      self._opt_sharding())

    def _opt_sharding(self, mesh=None, param_sharding=None):
        """Sharding pytree for opt_state: param-shaped state leaves
        (momenta, adam moments, master copies) follow their parameter's
        sharding; everything else (step counter, rng keys) is replicated.
        Used both for placement and for the step's in/out shardings — the
        two MUST agree, or the donated state input aliases an
        incompatibly-sharded output buffer (XLA INTERNAL size-mismatch).
        ``mesh``/``param_sharding`` override the trainer's own bindings
        so the integrity shadow replay can mirror the same structure
        onto a different same-shape mesh."""
        import jax

        if mesh is None:
            mesh = self.mesh
        if param_sharding is None:
            param_sharding = self._param_sharding
        repl = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())

        def shard_for(name, leaf):
            ps = param_sharding.get(name)
            p = self.params.get(name)
            if ps is not None and p is not None \
                    and hasattr(leaf, "shape") \
                    and tuple(leaf.shape) == tuple(p.shape):
                return ps
            return repl

        state = {
            k: jax.tree.map(lambda v, _k=k: shard_for(_k, v), s)
            for k, s in self.opt_state["state"].items()}
        return {**{k: repl for k in self.opt_state if k != "state"},
                "state": state}

    def _make_compute_loss(self):
        """The traced loss closure shared by the fused step and the
        elastic (grad-accumulation) executables — one definition so both
        paths compute bitwise-identical gradients."""
        import jax.numpy as jnp

        fwd = self._fwd
        loss_fn = self.loss_fn
        cdtype = self._compute_dtype

        from ..ndarray.ndarray import NDArray
        from ..jit import TraceSession

        def cast_in(tree):
            if cdtype is None:
                return tree
            return {k: (v.astype(cdtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in tree.items()}

        def compute_loss(params, aux, x, y, w=None):
            # AMP policy: bf16 params/activations in fwd+bwd; the cast sits
            # inside the grad so gradients land back in fp32 master dtype.
            # aux (BN moving stats, rng key) stays uncast: stats only feed
            # the f32 EMA update, and casting them to bf16 forces layout
            # copies into the BN-statistics fusions (PERF.md round 4)
            cp = cast_in(params)
            ca = aux
            if cdtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                x_c = x.astype(cdtype)
            else:
                x_c = x
            out, new_aux = fwd(cp, ca, x_c)
            if cdtype is not None:
                out = out.astype(jnp.float32)
                new_aux = {k: (v.astype(aux[k].dtype)
                               if jnp.issubdtype(aux[k].dtype, jnp.floating)
                               else v)
                           for k, v in new_aux.items()}
            with TraceSession() as sess:
                out_nd, y_nd = NDArray(out), NDArray(y)
                sess.note_created(out_nd)
                sess.note_created(y_nd)
                if w is None:
                    loss = loss_fn(out_nd, y_nd)
                else:
                    # per-token sample weight (pad masking): gluon losses
                    # broadcast_mul it into the per-element loss before
                    # their mean, so a weight normalized to sum to the
                    # element count turns the final .mean() into
                    # sum(l*mask)/sum(mask)
                    w_nd = NDArray(w)
                    sess.note_created(w_nd)
                    loss = loss_fn(out_nd, y_nd, w_nd)
            return loss.data_.mean(), new_aux

        return compute_loss

    def _capture_fingerprint(self):
        """Structural identity of this trainer's step programs for the
        capture/AOT compile path (mxnet_tpu.capture): everything that
        changes the traced program — params, optimizer + hyperparams
        (baked into make_update_fn here, unlike the gluon trainer's
        dynamic operands), mesh topology, sharding rules, compute dtype.
        A changed fingerprint is a re-capture, recorded in the retrace
        forensics; an unchanged one re-links the on-disk AOT artifact."""
        from .. import capture as _capture
        from ..resilience import integrity as _integrity

        parts = {
            "params": sorted((k, tuple(v.shape), str(v.dtype))
                             for k, v in self.params.items()),
            "aux": sorted((k, tuple(v.shape), str(v.dtype))
                          for k, v in self.aux.items()),
            # param avals alone can't distinguish relu from tanh or one
            # lambda loss body from another (docs/capture.md key schema)
            "net_struct": _capture.net_sig(self.net),
            "loss_code": _capture.code_sig(self.loss_fn),
            "optimizer": (str(self._optimizer),
                          sorted(self._optimizer_params.items())),
            "loss": getattr(self.loss_fn, "__qualname__",
                            type(self.loss_fn).__name__),
            "mesh": {str(a): int(s) for a, s in
                     zip(self.mesh.axis_names, self.mesh.devices.shape)},
            # host grouping changes the collective layout over a pod
            # (same axis sizes, different failure domains / ICI order)
            "pod": None if self._pod is None else
                   (int(self._pod.num_hosts),
                    int(self._pod.devices_per_host)),
            "rules": [(p.pattern, str(s)) for p, s in self._rules],
            "dtype": self._compute_dtype,
            "batch_axis": self._batch_axis,
            # kernel builders resolve Pallas block sizes from the tuned
            # schedule table at trace time (tune/), so a table edit is a
            # program change: fold the table token in so the next step()
            # re-traces instead of reusing the stale captured program
            "schedule": _capture._schedule_token(),
            # the in-graph step fingerprint adds an output to the traced
            # program (resilience.integrity) — an AOT artifact compiled
            # with the other setting must never false-hit
            "integrity": _integrity.fingerprint_enabled(),
        }
        return _capture.fingerprint(parts)

    def _capture_exec(self, fn, label, **kwargs):
        """Compile one step-program through the capture path (AOT
        persistence + retrace forensics + capture counters), noting a
        re-capture when the program fingerprint moved since the last
        build (mesh shrink, set_learning_rate)."""
        from .. import capture as _capture

        fp = self._capture_fingerprint()
        prev = getattr(self, "_capture_fp", None)
        if prev is not None and prev != fp:
            _capture.note_recapture(
                label, prev, fp,
                reason="step program rebind (mesh, hyperparameters or "
                       "kernel schedule table changed)")
        self._capture_fp = fp
        self._sched_token = _capture._schedule_token()
        return _capture.CapturedExec(fn, label=label, fingerprint=fp,
                                     **kwargs)

    def _build_step(self):
        import jax

        from ..resilience import integrity as _integrity

        update = self._update
        compute_loss = self._make_compute_loss()
        # in-graph step fingerprint (resilience.integrity): one extra
        # uint32 output of the SAME program — zero extra executables.
        # Armed at build time; the capture fingerprint folds the flag so
        # an AOT artifact compiled without it can never false-hit.
        fp_on = self._fp_armed = _integrity.fingerprint_enabled()

        def step(params, aux, opt_state, x, y):
            (loss, new_aux), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params, aux, x, y)
            new_params, new_opt = update(params, grads, opt_state)
            if fp_on:
                return (new_params, new_aux, new_opt, loss,
                        _integrity.step_fold(new_params, grads))
            return new_params, new_aux, new_opt, loss

        # opt_state shardings are pinned on BOTH sides: donation aliases
        # each state input buffer to its output, which is only valid when
        # the output keeps the input's sharding (XLA propagation would
        # otherwise shard tp-param momenta and break the aliasing)
        opt_sharding = self._opt_sharding()
        out_shardings = (self._param_sharding, self._aux_sharding,
                         opt_sharding, None) + ((None,) if fp_on else ())
        self._step = self._capture_exec(
            step, "sharded_step",
            in_shardings=(self._param_sharding, self._aux_sharding,
                          opt_sharding, self._batch_sharding,
                          self._batch_sharding),
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2), sig_argnums=(3, 4))

    def _build_masked_step(self):
        """The pad-masked variant of the fused step: one extra (B,) int32
        ``length`` operand (StreamBatch.length — per-row valid token
        counts), mask built in-graph from an iota compare so the program
        stays ONE executable across calls (length values are runtime
        data, never folded into the signature). The mask enters as a
        normalized per-token sample weight, making the step's scalar
        loss exactly sum(loss*mask)/sum(mask) — bitwise-equal to
        weighting with an explicitly precomputed host-side mask."""
        import jax
        import jax.numpy as jnp

        update = self._update
        compute_loss = self._make_compute_loss()

        def masked_loss(params, aux, x, y, length):
            t = int(x.shape[1])
            mask = (jnp.arange(t, dtype=jnp.int32)[None, :]
                    < length.astype(jnp.int32)[:, None]
                    ).astype(jnp.float32)
            # normalize so the loss's final mean over B*T elements
            # becomes the mean over the sum(mask) REAL tokens
            w = (mask * (float(mask.size) / jnp.sum(mask)))[..., None]
            return compute_loss(params, aux, x, y, w)

        from ..resilience import integrity as _integrity

        fp_on = self._fp_armed = _integrity.fingerprint_enabled()

        def step(params, aux, opt_state, x, y, length):
            (loss, new_aux), grads = jax.value_and_grad(
                masked_loss, has_aux=True)(params, aux, x, y, length)
            new_params, new_opt = update(params, grads, opt_state)
            if fp_on:
                return (new_params, new_aux, new_opt, loss,
                        _integrity.step_fold(new_params, grads))
            return new_params, new_aux, new_opt, loss

        opt_sharding = self._opt_sharding()
        out_shardings = (self._param_sharding, self._aux_sharding,
                         opt_sharding, None) + ((None,) if fp_on else ())
        self._step_masked = self._capture_exec(
            step, "sharded_step_masked",
            in_shardings=(self._param_sharding, self._aux_sharding,
                          opt_sharding, self._batch_sharding,
                          self._batch_sharding, self._batch_sharding),
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2), sig_argnums=(3, 4, 5))

    @classmethod
    def for_multihost(cls, net, loss_fn, optimizer="sgd",
                      optimizer_params=None, axes=None, coordinator=None,
                      num_processes=None, process_id=None, **kwargs):
        """Build a trainer over a GLOBAL mesh spanning every process of a
        multi-host job (the pod entry point: jax.distributed bootstrap +
        all-devices mesh — the TPU-native replacement for the reference's
        dist_sync worker group).

        Bootstraps jax.distributed from args or the DMLC_* env protocol
        (kvstore/dist.py) if not already initialized. `axes` is the mesh
        axes dict (default: pure data parallel over all global devices).
        In `step`, each process feeds its LOCAL batch shard (numpy) —
        shards are assembled into the global batch along the dp axis.
        """
        from ..kvstore.dist import init_distributed

        init_distributed(coordinator, num_processes, process_id)
        import jax

        devs = jax.devices()
        axes = dict(axes or {"dp": len(devs)})
        mesh = create_mesh(axes, devs)
        return cls(net, loss_fn, optimizer, optimizer_params, mesh=mesh,
                   **kwargs)

    @classmethod
    def for_pod(cls, net, loss_fn, optimizer="sgd", optimizer_params=None,
                axes=None, coordinator=None, num_processes=None,
                process_id=None, topology=None, **kwargs):
        """Build a trainer over a pod with HOST-level failure domains
        (docs/distributed.md). Like ``for_multihost`` — jax.distributed
        bootstraps from args or the DMLC_* env protocol when the job is
        multi-process — but the mesh device order is host-major
        (``parallel.mesh.pod_mesh``), the watchdog's pod liveness layer
        is configured with this process's place in it, and a lost host
        recovers by excising its WHOLE device slice in one pod-wide
        mesh shrink. A single process partitions its local devices into
        ``MXNET_TPU_POD_HOSTS`` simulated host groups instead, so the
        same recovery logic runs in-process (CI's simulated pod)."""
        from ..kvstore.dist import init_distributed
        from .mesh import pod_mesh

        init_distributed(coordinator, num_processes, process_id)
        mesh, topo = pod_mesh(axes, topology=topology)
        trainer = cls(net, loss_fn, optimizer, optimizer_params,
                      mesh=mesh, **kwargs)
        return trainer.bind_pod(topo)

    def bind_pod(self, topology):
        """Attach a ``parallel.mesh.PodTopology``: folds the host
        grouping into the capture fingerprint, enables host-domain
        recovery in ``step``, and declares this process's place to the
        watchdog's pod liveness layer (heartbeats + dead-host
        detection). Returns self for chaining."""
        from ..resilience import watchdog as _watchdog

        self._pod = topology
        if topology is not None:
            _watchdog.configure_pod(topology.num_hosts, topology.this_host)
        return self

    @property
    def pod(self):
        """The bound PodTopology, or None off-pod."""
        return self._pod

    def set_learning_rate(self, lr):
        """Change the learning rate (gluon Trainer.set_learning_rate
        parity). Hyperparameters are baked into the compiled step, so the
        next step() recompiles — schedule changes at epoch boundaries, not
        per step (use a lr_scheduler-style optimizer for per-step decay)."""
        self._optimizer_params["learning_rate"] = float(lr)
        _, update = make_update_fn(self._optimizer,
                                   dict(self._optimizer_params))
        self._update = update
        self._step = None  # rebuild (and recompile) with the new rate
        self._step_masked = None
        self._grads_fn = self._apply_fn = None  # elastic path too

    @property
    def learning_rate(self):
        return self._optimizer_params.get("learning_rate")

    @property
    def batch_sharding(self):
        """NamedSharding of the step's batch operands on the CURRENT
        mesh (re-derived on a mesh shrink) — the overlap handshake with
        the streaming input layer: ``io.stream.DevicePrefetcher.
        for_trainer`` places each prefetched batch with exactly this
        sharding, so ``step``'s own placement check
        (``is_equivalent_to``) skips the redundant device_put and the
        captured step consumes an already-resident batch."""
        return self._batch_sharding

    def _is_multiprocess(self):
        import jax

        return any(d.process_index != jax.process_index()
                   for d in self.mesh.devices.flat)

    def step(self, x, y, microbatches=None, length=None):
        """Run one sharded training step; returns the scalar loss.

        ``length`` (optional, (B,) int32 — ``StreamBatch.length``'s
        per-row valid token counts) masks pad tokens out of the loss:
        the step computes sum(loss*mask)/sum(mask) over the real tokens
        via a separate masked executable whose mask is built in-graph
        from an iota compare, so repeated masked calls stay ONE
        executable (length values are runtime data). The masked path is
        fused-only: combine it with ``microbatches`` > 1 and it raises.

        On a multi-process mesh, `x`/`y` are this process's LOCAL shard of
        the global batch (assembled with
        jax.make_array_from_process_local_data); single-process meshes
        take the full batch.

        ``microbatches=N`` executes the step as N accumulated
        microbatches (one optimizer update). Left at None, the step runs
        fused — and on ``RESOURCE_EXHAUSTED`` the elastic layer
        (resilience.elastic) transparently retries with doubling
        accumulation until it fits; the shrink is sticky for subsequent
        steps. The whole step runs under the step watchdog
        (MXNET_TPU_WATCHDOG_STEP_TIMEOUT).

        With a checkpoint manager attached (``checkpoint_manager=`` /
        ``enable_recovery``), a ``PeerLostError`` raised here — the
        ``peer_death`` fault, ``watchdog.mark_peer_dead``, or a
        collective stall with known-dead ranks — is survived in place:
        the mesh shrinks to the survivors, the latest reshardable
        checkpoint reloads onto it, sticky accumulation re-arms, and
        THIS batch re-runs (``last_recovery`` carries the restored
        manifest so schedule-aware drivers can rewind their data
        pipeline when the checkpoint cadence is coarser than one step).
        """
        with _obs_trace.span("train.sharded_step",
                             step=self._step_count + 1):
            return self._step_impl(x, y, microbatches, length)

    def _step_impl(self, x, y, microbatches, length=None):
        import warnings

        import jax

        from ..ndarray.ndarray import NDArray
        from ..resilience import elastic as _elastic
        from ..resilience import faults as _faults
        from ..resilience import watchdog as _watchdog

        # a schedule-table edit is a program change (kernel builders read
        # Pallas block sizes from the table at trace time): drop the
        # stale executables so the next build re-traces under the new
        # table — the retrace lands in the capture forensics, and the
        # AOT key (which folds the same token) can never false-hit
        if self._step is not None or self._step_masked is not None \
                or self._grads_fn is not None:
            from .. import capture as _capture

            if _capture._schedule_token() != getattr(self, "_sched_token",
                                                     None):
                self._step = None
                self._step_masked = None
                self._grads_fn = self._apply_fn = None
        if length is not None and microbatches is not None \
                and int(microbatches) != 1:
            raise ValueError(
                "length= (pad masking) runs the fused step only; "
                "accumulated microbatches would re-normalize the mask "
                "per slice — request microbatches=None")
        if length is not None:
            if self._step_masked is None:
                self._build_masked_step()
        elif self._step is None:
            self._build_step()
        if isinstance(x, NDArray):
            x = x.data_
        if isinstance(y, NDArray):
            y = y.data_
        if isinstance(length, NDArray):
            length = length.data_
        with _obs_trace.span("sharded.h2d"):
            if self._multiproc:
                import numpy as np

                def assemble(a):
                    # a single-device local array (NDArray.data_) is still
                    # a process-local shard: pull to host and assemble
                    # globally
                    if isinstance(a, jax.Array) and \
                            a.sharding.num_devices > 1:
                        return a  # already a global array
                    return jax.make_array_from_process_local_data(
                        self._batch_sharding, np.asarray(a))

                x = assemble(x)
                y = assemble(y)
                if length is not None:
                    length = assemble(length)
            else:
                # skip the put when the batch already sits on the mesh
                # with the right sharding (the steady-state training
                # loop) — the redundant device_put costs ~0.5% of step
                # time (PERF.md round-5 wrapper A/B)
                bs = self._batch_sharding
                if not (isinstance(x, jax.Array) and
                        x.sharding.is_equivalent_to(bs, x.ndim)):
                    x = jax.device_put(x, bs)
                if not (isinstance(y, jax.Array) and
                        y.sharding.is_equivalent_to(bs, y.ndim)):
                    y = jax.device_put(y, bs)
                if length is not None and not (
                        isinstance(length, jax.Array) and
                        length.sharding.is_equivalent_to(bs, length.ndim)):
                    length = jax.device_put(length, bs)
        self._step_count += 1
        _watchdog.note_step(self._step_count)
        from ..resilience import integrity as _integrity

        # retained pre-step snapshot for the shadow-replay audit (None
        # unless this step is on the audit cadence)
        snap = _integrity.snapshot_step(self, x, y)
        rows = int(x.shape[0])
        shards = self._batch_shards()

        def fit_count(k):
            # largest accumulation count <= k that divides the batch into
            # whole microbatches splittable over the CURRENT dp shards
            # (a short tail batch, or a just-shrunk mesh, must fall back,
            # never drop rows)
            while k > 1 and (rows % k or (rows // k) % max(1, shards)):
                k //= 2
            return max(1, k)

        if microbatches is not None:
            n = int(microbatches)
            if n < 1 or rows % n or (rows // n) % max(1, shards):
                raise ValueError(
                    f"microbatches={n} does not divide the {rows}-row "
                    f"batch into whole microbatches splittable over "
                    f"{shards} dp shard(s); accumulation must never "
                    "silently drop tail rows")
        else:
            # sticky n was validated against the batch size that OOMed
            n = fit_count(self._elastic_n)
        if length is not None:
            n = 1  # masked path is fused-only (no mask re-normalization
            # per microbatch slice); an OOM here surfaces, never shrinks
        while True:
            try:
                # one guard per ATTEMPT: a legitimate elastic retry
                # (recompile + N microbatch launches) gets a fresh
                # deadline rather than being killed mid-recovery by the
                # budget the failed fused attempt already spent
                with _watchdog.guard("step",
                                     detail="parallel.ShardedTrainer.step",
                                     step=self._step_count):
                    _watchdog.check_peers(
                        detail="parallel.ShardedTrainer.step")
                    _faults.maybe_hang("hang_step")
                    # a pod host wedged (not crashed) at the collective
                    # entry: the stall converts to a dead-host verdict
                    # via the watchdog's pod liveness layer
                    _faults.maybe_hang("host_hang_collective")
                    _faults.maybe_oom_step()
                    with _obs_trace.span("sharded.execute",
                                         microbatches=n):
                        if length is not None:
                            if self._step_masked is None:  # mesh rebound
                                self._build_masked_step()
                            outs = self._step_masked(self.params, self.aux,
                                                     self.opt_state, x, y,
                                                     length)
                            (self.params, self.aux, self.opt_state,
                             loss) = outs[:4]
                            self._last_fp_out = \
                                outs[4] if len(outs) > 4 else None
                        elif n <= 1:
                            if self._step is None:  # mesh rebound mid-retry
                                self._build_step()
                            outs = self._step(self.params, self.aux,
                                              self.opt_state, x, y)
                            (self.params, self.aux, self.opt_state,
                             loss) = outs[:4]
                            self._last_fp_out = \
                                outs[4] if len(outs) > 4 else None
                        else:
                            loss = self._accum_step(n, x, y)
                    # SDC fault hooks land AFTER the step (corrupting the
                    # new state) and the shadow-replay audit runs INSIDE
                    # the attempt loop: a transient verdict rolls back and
                    # retries this batch, a sticky-device verdict raises
                    # PeerLostError into the same mesh-shrink recovery
                    # path as a dead peer
                    if self._last_fp_out is not None:
                        _integrity.note_fingerprint_step()
                    self.params = _faults.maybe_sdc_bitflip_param(
                        self.params)
                    self.params = _faults.maybe_sdc_sticky_param(
                        self.params, self.mesh)
                    if snap is not None:
                        verdict = _integrity.audit_step(
                            self, snap, n=n, length=length,
                            live_fp=self._last_fp_out)
                        if verdict == "retry":
                            continue
                        snap = None
                break
            except _watchdog.PeerLostError as e:
                # a dead peer is unrecoverable in place — but with a
                # checkpoint manager attached the run survives it: shrink
                # the mesh to the survivors, reload the latest
                # reshardable checkpoint onto it, and re-run this batch
                if self._ckpt_mgr is None \
                        or not _elastic.mesh_shrink_enabled() \
                        or (self._multiproc and self._pod is None):
                    # multi-process recovery needs host failure domains
                    # (bind_pod/for_pod): without the pod topology there
                    # is no survivable shrink of a global mesh
                    raise
                x, y = self._recover_peer_loss(e, x, y)
                snap = None  # pre-step snapshot is stale after a
                # checkpoint restore — the re-run batch is not audited
                if length is not None:
                    length = jax.device_put(length, self._batch_sharding)
                shards = self._batch_shards()
                if microbatches is not None:
                    if rows % n or (rows // n) % max(1, shards):
                        raise ValueError(
                            f"explicit microbatches={n} no longer splits "
                            f"the {rows}-row batch over the shrunk "
                            f"{shards}-shard mesh; request a compatible "
                            "schedule") from e
                else:
                    n = fit_count(max(n, self._elastic_n))
                continue
            except Exception as e:
                if microbatches is not None or length is not None \
                        or not (_elastic.enabled()
                                and _elastic.is_oom_error(e)):
                    # explicit schedules are the caller's contract —
                    # elastic retry applies only to the implicit path
                    raise
                if self._multiproc:
                    # microbatch slicing of a non-fully-addressable
                    # global batch is an eager cross-process op jax
                    # cannot run; surface the REAL OOM rather than a
                    # masked addressability error mid-retry
                    warnings.warn(
                        "step OOM on a multi-process mesh: elastic "
                        "microbatch retry is single-process only "
                        "(docs/resilience.md) — lower the per-host "
                        "batch or request microbatches= explicitly "
                        "at a size every process can slice locally")
                    raise
                _elastic._STATS["elastic_oom_events"] += 1
                self._check_state_alive(e)
                nxt = _elastic.next_microbatches(n, rows, shards)
                if nxt is None:
                    raise
                _elastic._STATS["elastic_shrinks"] += 1
                warnings.warn(
                    f"training step OOM at {n} microbatch(es) over a "
                    f"{rows}-row batch; retrying as {nxt} accumulated "
                    f"microbatches of {rows // nxt} rows")
                n = nxt
        if microbatches is None and n > self._elastic_n:
            self._elastic_n = n  # sticky: don't re-OOM every step (a
            # short tail batch's fallback must not discard the shrink)
        if _integrity.preempt_requested() or _faults.maybe_preempt():
            # SIGTERM (or a drilled preempt): the in-flight step is done —
            # emergency checkpoint, drain, exit cleanly
            _integrity.preempt_exit(self, loss=loss)
        return loss

    def _check_state_alive(self, cause):
        """A fused step donates params/aux/opt_state; if the failure
        happened after donation invalidated any of them, a retry would
        compute on deleted buffers. Surface that explicitly instead."""
        import jax

        leaves = (list(self.params.values()) + list(self.aux.values())
                  + jax.tree_util.tree_leaves(self.opt_state))
        for v in leaves:
            if getattr(v, "is_deleted", lambda: False)():
                raise RuntimeError(
                    "step failed after its donated inputs were "
                    "invalidated; elastic retry is impossible — "
                    "restore from the last checkpoint "
                    "(resilience.CheckpointManager.restore_latest)"
                ) from cause

    @staticmethod
    def _host_local_batch(arr):
        """A batch operand safe to re-place on a shrunk mesh. On a real
        pod the assembled global batch is NOT fully addressable and
        jax cannot reshard it onto the survivors' smaller mesh — fall
        back to this host's own rows (its addressable shards, in batch
        order), which is exactly what this process fed ``step``."""
        import jax

        if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
            return arr
        import numpy as np

        shards = {tuple(sl.start or 0 for sl in s.index):
                  np.asarray(s.data) for s in arr.addressable_shards}
        return np.concatenate(
            [shards[k] for k in sorted(shards)], axis=0)

    def _recover_peer_loss(self, err, x, y):
        """Mesh-shrink resume: rebuild a smaller mesh from the surviving
        ranks, reload the latest (reshardable, v2) checkpoint onto it,
        re-arm the sticky elastic accumulation so the per-device
        microbatch stays where it last fit, and return the batch
        re-placed for the new mesh so the caller retries this step.
        The recovery is logged, counted (``watchdog_peer_recoveries``,
        ``elastic_mesh_shrinks``), and stamped into the crash report
        (``watchdog.note_peer_recovery``). Raises if no viable smaller
        mesh or no valid checkpoint exists — then the PeerLostError was
        genuinely terminal."""
        import warnings

        import jax

        from ..resilience import elastic as _elastic
        from ..resilience import watchdog as _watchdog
        from .mesh import MeshShrinkError, shrink_mesh

        if self._pod is not None:
            hosts = (list(getattr(err, "hosts", ()) or ())
                     or _watchdog.dead_hosts())
            if hosts:
                return self._recover_host_loss(err, x, y, hosts)
        dead = _watchdog.dead_peers() or list(getattr(err, "ranks", ()))
        old_axes = {str(a): int(s) for a, s in
                    zip(self.mesh.axis_names, self.mesh.devices.shape)}
        try:
            new_mesh = shrink_mesh(self.mesh, dead,
                                   batch_axis=self._batch_axis)
        except MeshShrinkError:
            raise err  # nothing viable left: the loss really is terminal
        import math

        batch_names = self._batch_axis_names()
        old_dp = math.prod(int(old_axes.get(a, 1)) for a in batch_names)
        new_axes = {str(a): int(s) for a, s in
                    zip(new_mesh.axis_names, new_mesh.devices.shape)}
        new_dp = math.prod(int(new_axes.get(a, 1)) for a in batch_names)
        self._bind_mesh(new_mesh)
        # the excised ranks are no longer part of the job: re-admit the
        # collectives (kvstore guards included) before the restore's
        # device_puts and the retried step
        _watchdog.reset_peers()
        manifest = self._ckpt_mgr.restore_latest(trainer=self)
        if manifest is None:
            raise RuntimeError(
                f"peer rank(s) {dead} lost and no valid checkpoint exists "
                f"to reload onto the shrunk {new_dp}-shard mesh; cannot "
                "recover") from err
        self._elastic_n = _elastic.rearm_microbatches(
            self._elastic_n, old_dp, new_dp)
        _elastic._STATS["elastic_mesh_shrinks"] += 1
        _watchdog.note_peer_recovery(err, manifest, old_axes, new_axes)
        self.last_recovery = manifest
        axis_label = "x".join(batch_names)
        warnings.warn(
            f"peer rank(s) {dead} lost: resumed from checkpoint step "
            f"{manifest.get('step')} on a mesh shrunk "
            f"{old_dp} -> {new_dp} '{axis_label}' shard(s); "
            "this step re-runs on the survivors (capacity is reduced — "
            "see the crash report)")
        bs = self._batch_sharding
        x, y = self._host_local_batch(x), self._host_local_batch(y)
        return jax.device_put(x, bs), jax.device_put(y, bs)

    def _recover_host_loss(self, err, x, y, hosts):
        """Host-domain mesh-shrink resume (docs/distributed.md): the
        whole failure domain — every device rank of the dead host(s) —
        leaves the mesh in ONE shrink. The coordinated restart:
        survivors barrier (so nobody restores against a checkpoint a
        faster peer is about to supersede), the global mesh is rebuilt
        host-major from the surviving hosts (renumbered 0..k-1), the
        watchdog pod layer is re-declared for the smaller pod at the
        next generation, and the latest reshardable v2 checkpoint is
        reloaded onto the shrunk topology. Raises when no host-aligned
        shrink exists or no valid checkpoint survives — then the loss
        was genuinely terminal."""
        import math
        import warnings

        import jax

        from ..resilience import elastic as _elastic
        from ..resilience import watchdog as _watchdog
        from .mesh import MeshShrinkError, shrink_mesh_hosts

        hosts = sorted({int(h) for h in hosts})
        old_axes = {str(a): int(s) for a, s in
                    zip(self.mesh.axis_names, self.mesh.devices.shape)}
        try:
            _watchdog.pod_barrier()
        except _watchdog.PeerLostError as late:
            # a survivor died before making the barrier: fold it into
            # this recovery instead of recovering twice
            hosts = sorted(set(hosts) | set(getattr(late, "hosts", ())))
        try:
            new_mesh, new_topo, kept = shrink_mesh_hosts(
                self.mesh, hosts, self._pod,
                batch_axis=self._batch_axis)
        except MeshShrinkError:
            raise err  # no host-aligned smaller mesh: genuinely terminal
        batch_names = self._batch_axis_names()
        old_dp = math.prod(int(old_axes.get(a, 1)) for a in batch_names)
        new_axes = {str(a): int(s) for a, s in
                    zip(new_mesh.axis_names, new_mesh.devices.shape)}
        new_dp = math.prod(int(new_axes.get(a, 1)) for a in batch_names)
        gen = (_watchdog.pod_info() or {}).get("generation", 0) + 1
        self._bind_mesh(new_mesh)
        self._pod = new_topo
        if getattr(self._ckpt_mgr, "_pod", None) is not None:
            # the manager's distributed commit must follow the shrunk,
            # renumbered topology too
            self._ckpt_mgr.bind_pod(new_topo)
        # the dead generation's bookkeeping must not leak into the
        # renumbered pod: fresh peer set, fresh host registry/heartbeats
        _watchdog.reset_peers()
        _watchdog.configure_pod(new_topo.num_hosts, new_topo.this_host,
                                generation=gen)
        manifest = self._ckpt_mgr.restore_latest(trainer=self)
        if manifest is None:
            raise RuntimeError(
                f"pod host(s) {hosts} lost and no valid checkpoint "
                f"exists to reload onto the shrunk {new_dp}-shard mesh; "
                "cannot recover") from err
        self._elastic_n = _elastic.rearm_microbatches(
            self._elastic_n, old_dp, new_dp)
        _elastic._STATS["elastic_mesh_shrinks"] += 1
        _watchdog.note_peer_recovery(err, manifest, old_axes, new_axes)
        self.last_recovery = manifest
        axis_label = "x".join(batch_names)
        warnings.warn(
            f"pod host(s) {hosts} lost: resumed from checkpoint step "
            f"{manifest.get('step')} on a pod shrunk to host(s) "
            f"{list(kept)} ({old_dp} -> {new_dp} '{axis_label}' "
            "shard(s)); this step re-runs on the survivors (capacity is "
            "reduced — see the crash report)")
        bs = self._batch_sharding
        x, y = self._host_local_batch(x), self._host_local_batch(y)
        return jax.device_put(x, bs), jax.device_put(y, bs)

    def _build_elastic(self):
        """Two executables for the accumulated path: a NON-donating
        gradient function (its params are reused by every microbatch and
        by any further retry) and an apply function for the single
        optimizer update. Gradients land in the parameter shardings so
        accumulation never reshards."""
        import jax

        update = self._update
        compute_loss = self._make_compute_loss()

        def grads_fn(params, aux, x, y):
            (loss, new_aux), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params, aux, x, y)
            return grads, new_aux, loss

        # the microbatch shapes key the signature: an elastic shrink
        # re-captures at the smaller batch and the re-capture lands in
        # the retrace forensics instead of recompiling silently
        self._grads_fn = self._capture_exec(
            grads_fn, "sharded_grads",
            in_shardings=(self._param_sharding, self._aux_sharding,
                          self._batch_sharding, self._batch_sharding),
            out_shardings=(self._param_sharding, self._aux_sharding, None),
            sig_argnums=(2, 3))

        def apply_fn(params, grads, opt_state):
            return update(params, grads, opt_state)

        opt_sharding = self._opt_sharding()
        self._apply_fn = self._capture_exec(
            apply_fn, "sharded_apply",
            in_shardings=(self._param_sharding, self._param_sharding,
                          opt_sharding),
            out_shardings=(self._param_sharding, opt_sharding))

    def _accum_step(self, n, x, y):
        """One optimizer update from n accumulated microbatches: grads
        are computed per microbatch on the SAME params, summed, divided
        by n (mean-of-means == full-batch mean for equal slices), then
        applied once. aux chains through microbatches sequentially.
        Bitwise identical to an explicit step(..., microbatches=n)."""
        import jax
        import jax.numpy as jnp

        from ..resilience import elastic as _elastic
        from ..resilience import faults as _faults

        if self._grads_fn is None:
            self._build_elastic()
        _elastic._STATS["elastic_accum_steps"] += 1
        rows = int(x.shape[0])
        mb = rows // n
        params, aux, opt_state = self.params, self.aux, self.opt_state
        acc = None
        loss_sum = None
        bs = self._batch_sharding
        for i in range(n):
            sl = slice(i * mb, (i + 1) * mb)
            # an eager slice of a dp-sharded batch comes back replicated;
            # re-place it so the grad executable sees the batch sharding
            x_i = jax.device_put(x[sl], bs)
            y_i = jax.device_put(y[sl], bs)
            grads, aux, loss = self._grads_fn(params, aux, x_i, y_i)
            acc = grads if acc is None else jax.tree.map(jnp.add, acc, grads)
            loss_sum = loss if loss_sum is None else loss_sum + loss
        inv = 1.0 / n
        acc = jax.tree.map(lambda g: g * inv, acc)
        acc = _faults.maybe_sdc_bitflip_grad(acc)
        params, opt_state = self._apply_fn(params, acc, opt_state)
        self.params, self.aux, self.opt_state = params, aux, opt_state
        from ..resilience import integrity as _integrity

        if _integrity.fingerprint_enabled():
            # the accumulated path has no single fused executable to grow
            # an output on — fold the same fingerprint host-side over the
            # applied params and the accumulated (divided) grads
            import numpy as np

            self._last_fp_out = np.uint32(_integrity.step_fold_host(
                {k: np.asarray(v) for k, v in params.items()},
                {k: np.asarray(v) for k, v in acc.items()}))
        else:
            self._last_fp_out = None
        return loss_sum / n

    def get_states_bytes(self):
        """Serialize opt_state (host-side npz keyed by pytree path) — the
        byte form consumed by resilience.CheckpointManager and
        save_states."""
        import io

        import numpy as np

        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(self.opt_state)
        entries = {jax.tree_util.keystr(path): np.asarray(leaf)
                   for path, leaf in flat}
        buf = io.BytesIO()
        np.savez(buf, **entries)
        return buf.getvalue()

    def set_states_bytes(self, data):
        """Restore opt_state from get_states_bytes output. Every leaf is
        re-placed with its original NamedSharding (via _opt_sharding), so
        sharded optimizer state comes back sharded — loading it
        replicated would break step donation aliasing AND silently
        multiply per-device memory."""
        import io

        import numpy as np

        f = np.load(io.BytesIO(data), allow_pickle=False)
        self.set_states_arrays({k: f[k] for k in f.files})

    def set_states_arrays(self, mapping):
        """Restore opt_state from a {keystr: host array} mapping (the
        form v2 reshardable checkpoints reassemble shard payloads into).
        Each leaf is re-placed with THIS trainer's NamedSharding on its
        CURRENT mesh — which is exactly how checkpoint state saved on a
        different dp-shard count lands correctly after a mesh shrink.
        Validates the mapping covers the opt_state tree exactly."""
        import numpy as np

        import jax
        import jax.numpy as jnp

        stored = dict(mapping)
        shardings = self._opt_sharding()

        def restore(path, leaf, sh):
            key = jax.tree_util.keystr(path)
            if key not in stored:
                raise ValueError(
                    f"trainer states file is missing opt_state leaf {key} "
                    "(saved from a different optimizer/model?)")
            arr = stored.pop(key)
            if tuple(arr.shape) != tuple(np.shape(leaf)):
                raise ValueError(
                    f"opt_state leaf {key} has shape {arr.shape} in the "
                    f"states file but {np.shape(leaf)} in this trainer")
            return jax.device_put(jnp.asarray(arr), sh)

        new_state = jax.tree_util.tree_map_with_path(
            restore, self.opt_state, shardings)
        if stored:
            raise ValueError(
                "trainer states file has extra opt_state leaves "
                f"{sorted(stored)[:3]} (saved from a different "
                "optimizer/model?)")
        self.opt_state = new_state

    def save_states(self, fname):
        """Save optimizer state to a file, atomically (temp + fsync +
        rename); counterpart of gluon Trainer.save_states."""
        from ..resilience.checkpoint import atomic_write_bytes

        atomic_write_bytes(fname, self.get_states_bytes())

    def load_states(self, fname):
        """Load optimizer state saved by save_states, restoring each
        leaf's mesh sharding."""
        with open(fname, "rb") as f:
            self.set_states_bytes(f.read())

    def sync_to_net(self):
        """Write the sharded parameter state back into the gluon net
        (collapsed to one device so eager ops keep working)."""
        import jax

        from .functional import RNG_KEY
        from .. import random as _random

        dev = self.mesh.devices.flat[0]
        multiproc = self._multiproc

        def fetch(v):
            if multiproc:
                # replicated values: the local shard IS the full array;
                # cross-process-sharded params would need an allgather
                shard = v.addressable_shards[0]
                if shard.data.shape != v.shape:
                    raise NotImplementedError(
                        "sync_to_net on a multi-host mesh supports "
                        "replicated params only; allgather sharded params "
                        "explicitly")
                return jax.device_put(shard.data, jax.local_devices()[0])
            return jax.device_put(v, dev)

        for name, p in self.net.collect_params().items():
            if name in self.params:
                p.data()._set_data(fetch(self.params[name]))
            elif name in self.aux:
                p.data()._set_data(fetch(self.aux[name]))
        if RNG_KEY in self.aux:
            _random.generator_key()._set_data(fetch(self.aux[RNG_KEY]))

    @property
    def last_fingerprint(self):
        """uint32 in-graph fingerprint of the last executed step, or None
        when fingerprinting is off (resilience.integrity). Reading it is
        the only host sync — the step itself never blocks on it."""
        if self._last_fp_out is None:
            return None
        import numpy as np

        return int(np.asarray(self._last_fp_out))

    def integrity_replay(self, mesh, params, aux, opt_state, x, y,
                         microbatches=1, length=None):
        """Re-execute ONE training step from host-side pre-step state on
        an alternate same-shape mesh (the shadow slice of the SDC audit,
        resilience.integrity.audit_step). Mirrors the live variant
        exactly — fused, pad-masked, or n-microbatch accumulation — since
        the variants are not bitwise-interchangeable (different grad
        arithmetic); the shadow mesh keeps the live mesh's shape and axis
        names so GSPMD emits the same collective structure and float
        reduction order. Returns ``(host new_params dict, uint32
        fingerprint or None)``. The trainer's own state, mesh, and
        executables are untouched; replay executables are plain
        non-donating jits cached per (shadow devices, variant, capture
        fingerprint)."""
        import numpy as np

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..resilience import integrity as _integrity

        fp_on = _integrity.fingerprint_enabled()
        n = max(1, int(microbatches))
        key = (tuple(int(d.id) for d in mesh.devices.flat), n,
               length is not None, fp_on, self._capture_fingerprint())
        cached = getattr(self, "_replay_cache", None)
        if cached is not None and cached[0] == key:
            shards, fns = cached[1], cached[2]
        else:
            param_sh = {k: NamedSharding(mesh, self._spec_for(k))
                        for k in params}
            repl = NamedSharding(mesh, P())
            aux_sh = {k: repl for k in aux}
            batch_sh = NamedSharding(mesh, P(self._batch_axis))
            opt_sh = self._opt_sharding(mesh=mesh,
                                        param_sharding=param_sh)
            shards = (param_sh, aux_sh, batch_sh, opt_sh)
            update = self._update
            compute_loss = self._make_compute_loss()
            if length is not None:
                def masked_loss(p, a, xx, yy, ll):
                    t = int(xx.shape[1])
                    mask = (jnp.arange(t, dtype=jnp.int32)[None, :]
                            < ll.astype(jnp.int32)[:, None]
                            ).astype(jnp.float32)
                    w = (mask * (float(mask.size) / jnp.sum(mask))
                         )[..., None]
                    return compute_loss(p, a, xx, yy, w)

                def rstep(p, a, o, xx, yy, ll):
                    (_loss, _na), grads = jax.value_and_grad(
                        masked_loss, has_aux=True)(p, a, xx, yy, ll)
                    new_p, _no = update(p, grads, o)
                    fp = _integrity.step_fold(new_p, grads) \
                        if fp_on else jnp.uint32(0)
                    return new_p, fp

                fns = jax.jit(
                    rstep,
                    in_shardings=(param_sh, aux_sh, opt_sh, batch_sh,
                                  batch_sh, batch_sh),
                    out_shardings=(param_sh, None))
            elif n <= 1:
                def rstep(p, a, o, xx, yy):
                    (_loss, _na), grads = jax.value_and_grad(
                        compute_loss, has_aux=True)(p, a, xx, yy)
                    new_p, _no = update(p, grads, o)
                    fp = _integrity.step_fold(new_p, grads) \
                        if fp_on else jnp.uint32(0)
                    return new_p, fp

                fns = jax.jit(
                    rstep,
                    in_shardings=(param_sh, aux_sh, opt_sh, batch_sh,
                                  batch_sh),
                    out_shardings=(param_sh, None))
            else:
                def grads_fn(p, a, xx, yy):
                    (loss, new_a), grads = jax.value_and_grad(
                        compute_loss, has_aux=True)(p, a, xx, yy)
                    return grads, new_a, loss

                def apply_fn(p, g, o):
                    return update(p, g, o)

                fns = (
                    jax.jit(grads_fn,
                            in_shardings=(param_sh, aux_sh, batch_sh,
                                          batch_sh),
                            out_shardings=(param_sh, aux_sh, None)),
                    jax.jit(apply_fn,
                            in_shardings=(param_sh, param_sh, opt_sh),
                            out_shardings=(param_sh, opt_sh)))
            self._replay_cache = (key, shards, fns)
        param_sh, aux_sh, batch_sh, opt_sh = shards
        p_dev = {k: jax.device_put(np.asarray(v), param_sh[k])
                 for k, v in params.items()}
        a_dev = {k: jax.device_put(np.asarray(v), aux_sh[k])
                 for k, v in aux.items()}
        o_dev = jax.tree.map(
            lambda leaf, sh: jax.device_put(np.asarray(leaf), sh),
            opt_state, opt_sh)
        x_dev = jax.device_put(np.asarray(x), batch_sh)
        y_dev = jax.device_put(np.asarray(y), batch_sh)
        if length is not None:
            l_dev = jax.device_put(np.asarray(length), batch_sh)
            new_p, fp = fns(p_dev, a_dev, o_dev, x_dev, y_dev, l_dev)
        elif n <= 1:
            new_p, fp = fns(p_dev, a_dev, o_dev, x_dev, y_dev)
        else:
            gfn, afn = fns
            rows = int(x_dev.shape[0])
            mb = rows // n
            acc = None
            a_cur = a_dev
            for i in range(n):
                sl = slice(i * mb, (i + 1) * mb)
                x_i = jax.device_put(x_dev[sl], batch_sh)
                y_i = jax.device_put(y_dev[sl], batch_sh)
                grads, a_cur, _loss = gfn(p_dev, a_cur, x_i, y_i)
                acc = grads if acc is None \
                    else jax.tree.map(jnp.add, acc, grads)
            inv = 1.0 / n
            acc = jax.tree.map(lambda g: g * inv, acc)
            new_p, _o = afn(p_dev, acc, o_dev)
            host_p = {k: np.asarray(v) for k, v in new_p.items()}
            fp = np.uint32(_integrity.step_fold_host(
                host_p,
                {k: np.asarray(v) for k, v in acc.items()})) \
                if fp_on else None
            return host_p, (None if fp is None else int(fp))
        host_p = {k: np.asarray(v) for k, v in new_p.items()}
        return host_p, (int(np.asarray(fp)) if fp_on else None)
