"""Optimizers.

Parity: python/mxnet/optimizer/optimizer.py (Optimizer base w/ registry,
create_state, multi-precision master weights :234, 17 optimizers) backed by
the fused update *operators* in ops/optimizer_ops.py — the same split as the
reference, where state math lives in src/operator/optimizer_op.cc kernels.
Each update mutates the weight cell in place; inside a traced train step the
whole update fuses into the step executable with donated buffers.
"""
from __future__ import annotations

import math
import pickle

import numpy as _np

from ..base import MXNetError, _Registry
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray, zeros as nd_zeros

__all__ = ["Optimizer", "Updater", "get_updater", "create", "register"]

_OPT_REGISTRY = _Registry("optimizer")


def register(klass):
    _OPT_REGISTRY.register(klass)
    return klass


def create(name, **kwargs):
    return _OPT_REGISTRY.get(name)(**kwargs)


class Optimizer:
    """Base optimizer (optimizer.py:53). Learning-rate/wd multipliers come
    from param_dict / idx2name attributes exactly like the reference."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}
        # bias/gamma/beta get zero weight decay by default, unconditionally
        # (reference Optimizer.__init__ calls set_wd_mult({}) itself — the
        # defaults must not depend on whether a user ever sets a mult)
        self.set_wd_mult({})
        # aggregate_num > 1 asks the Trainer to run updates through an
        # engine.bulk lazy segment of that many update ops, the TPU-native
        # stand-in for the reference's MXNET_OPTIMIZER_AGGREGATION_SIZE
        # multi-tensor kernels (0 keeps per-op eager dispatch)
        self.aggregate_num = int(aggregate_num)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == _np.float16:
            w32 = weight.astype(_np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            inner_state, w32 = state
            g32 = grad.astype(_np.float32)
            self.update(index, w32, g32, inner_state)
            weight._set_data(w32.astype(_np.float16)._data)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; use it to change the rate")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)
        for name in self.idx2name.values():
            if name.endswith(("_bias", "_gamma", "_beta")) and name not in self.wd_mult:
                self.wd_mult[name] = 0.0

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = (self.lr_scheduler(self.num_update) if self.lr_scheduler
              else self.lr)
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= getattr(self.param_dict[name], "lr_mult", 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= getattr(self.param_dict[name], "wd_mult", 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD + momentum (optimizer.py:527); fused kernel sgd(_mom)_update."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, weight.context, weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            _nd.imperative_invoke("sgd_mom_update", weight, grad, state,
                                  momentum=self.momentum, **kw)
        else:
            _nd.imperative_invoke("sgd_update", weight, grad, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            if self.momentum != 0.0:
                mom, w32 = state
                _nd.imperative_invoke("mp_sgd_mom_update", weight, grad, mom,
                                      w32, momentum=self.momentum,
                                      **self._common_kwargs(index))
                self._update_count(index)
            else:
                (_, w32) = state if isinstance(state, tuple) else (None, state)
                _nd.imperative_invoke("mp_sgd_update", weight, grad, w32,
                                      **self._common_kwargs(index))
                self._update_count(index)
        else:
            self.update(index, weight, grad, state)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, weight.context, weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            _nd.imperative_invoke("nag_mom_update", weight, grad, state,
                                  momentum=self.momentum, **kw)
        else:
            _nd.imperative_invoke("sgd_update", weight, grad, **kw)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr as in the reference
        kw["lr"] *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        _nd.imperative_invoke("adam_update", weight, grad, mean, var,
                              beta1=self.beta1, beta2=self.beta2,
                              epsilon=self.epsilon, **kw)


@register
class AdamW(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        mean, var = state
        kw = self._common_kwargs(index)
        _nd.imperative_invoke("adamw_update", weight, grad, mean, var,
                              beta1=self.beta1, beta2=self.beta2,
                              epsilon=self.epsilon, eta=self.eta, **kw)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd_zeros(weight.shape, weight.context, weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        state._set_data((state + g * g)._data)
        delta = g / ((state ** 0.5) + self.float_stable_eps) + wd * weight
        weight._set_data((weight - lr * delta)._data)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * g * g)._data)
        cur_delta = ((acc_delta + self.epsilon) ** 0.5 /
                     (acc_g + self.epsilon) ** 0.5) * g
        acc_delta._set_data((self.rho * acc_delta + (1 - self.rho) * cur_delta * cur_delta)._data)
        weight._set_data(((1 - wd) * weight - cur_delta)._data)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered, self.epsilon = centered, epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd_zeros(weight.shape, weight.context, weight.dtype)
        if self.centered:
            return (z(), z(), z())
        return z()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.centered:
            n, g_avg, delta = state
            _nd.imperative_invoke("rmspropalex_update", weight, grad, n, g_avg,
                                  delta, gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, **kw)
        else:
            _nd.imperative_invoke("rmsprop_update", weight, grad, state,
                                  gamma1=self.gamma1, epsilon=self.epsilon, **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        kw = self._common_kwargs(index)
        _nd.imperative_invoke("ftrl_update", weight, grad, z, n,
                              lamda1=self.lamda1, beta=self.beta, **kw)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        m, u = state
        m._set_data((self.beta1 * m + (1 - self.beta1) * g)._data)
        u._set_data(_nd.imperative_invoke("broadcast_maximum",
                                          u * self.beta2, _nd.imperative_invoke("abs", g)[0])[0]._data)
        weight._set_data((weight - lr * m / (u + 1e-8))._data)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon, self.schedule_decay = epsilon, schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        m._set_data((self.beta1 * m + (1 - self.beta1) * g)._data)
        v._set_data((self.beta2 * v + (1 - self.beta2) * g * g)._data)
        g_prime = g / (1 - self.m_schedule)
        m_prime = m / (1 - m_schedule_next)
        v_prime = v / (1 - self.beta2 ** t)
        m_bar = (1 - momentum_t) * g_prime + momentum_t_1 * m_prime
        weight._set_data((weight - lr * m_bar / ((v_prime ** 0.5) + self.epsilon))._data)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, weight.context, weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            _nd.imperative_invoke("signum_update", weight, grad, state,
                                  momentum=self.momentum, wd_lh=self.wd_lh, **kw)
        else:
            _nd.imperative_invoke("signsgd_update", weight, grad, **kw)


@register
class SGLD(Optimizer):
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        from .. import random as _random

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        noise = _random.normal(0, math.sqrt(lr), weight.shape,
                               dtype=str(weight.dtype))
        weight._set_data((weight - lr / 2 * g + noise)._data)


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype)
                if self.momentum != 0.0 else None,
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, prev_w = state
        d = g + wd * weight + self.lamda * g * g * (weight - prev_w)
        if mom is not None:
            mom._set_data((self.momentum * mom - lr * d)._data)
            upd = mom
        else:
            upd = -lr * d
        prev_w._set_data(weight._data)
        weight._set_data((weight + upd)._data)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = lambda: nd_zeros(weight.shape, weight.context, weight.dtype)
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        d, v, z = state
        v._set_data((self.beta2 * v + (1 - self.beta2) * g * g)._data)
        d_t = (1 - self.beta1 ** t) / lr * ((v / (1 - self.beta2 ** t)) ** 0.5 + self.epsilon)
        sigma_t = d_t - self.beta1 * d
        z._set_data((self.beta1 * z + (1 - self.beta1) * g - sigma_t * weight)._data)
        d._set_data(d_t._data)
        weight._set_data((-z / d_t)._data)


@register
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, weight.context, weight.dtype),
                nd_zeros(weight.shape, weight.context, weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        mean, var = state
        kw = {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
              "t": t, "bias_correction": self.bias_correction, "wd": wd,
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        # phase1 returns the adam-direction; means/vars updated inline
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        mean._set_data((self.beta1 * mean + (1 - self.beta1) * g)._data)
        var._set_data((self.beta2 * var + (1 - self.beta2) * g * g)._data)
        m = mean / (1 - self.beta1 ** t) if self.bias_correction else mean
        v = var / (1 - self.beta2 ** t) if self.bias_correction else var
        update = m / ((v ** 0.5) + self.epsilon) + wd * weight
        r1 = weight.norm()
        r2 = update.norm()
        _nd.imperative_invoke("lamb_update_phase2", weight, update, r1, r2,
                              lr=lr,
                              lower_bound=self.lower_bound if self.lower_bound is not None else -1.0,
                              upper_bound=self.upper_bound if self.upper_bound is not None else -1.0)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (optimizer.py:798)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, weight.context, weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w_norm = float(weight.norm().asscalar())
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _nd.clip(g, -self.clip_gradient, self.clip_gradient)
        g_norm = float(g.norm().asscalar())
        if w_norm > 0 and g_norm > 0:
            lr *= self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon)
        if state is not None:
            state._set_data((self.momentum * state - lr * (g + wd * weight))._data)
            weight._set_data((weight + state)._data)
        else:
            weight._set_data((weight - lr * (g + wd * weight))._data)


@register
class LBSGD(SGD):
    """Large-batch SGD with warmup (optimizer.py:1058) — LARS-style scaling."""

    def __init__(self, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(**kwargs)
        self.warmup_strategy = warmup_strategy


@register
class Test(Optimizer):
    """The reference's debugging optimizer (optimizer.py:2032)."""

    def create_state(self, index, weight):
        return nd_zeros(weight.shape, weight.context, weight.dtype)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad)._data)
        state._set_data(weight._data)


class Updater:
    """Applies an optimizer per key (bottom of optimizer.py). Serializable
    for Module.save_optimizer_states parity."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray):
            self._update_row_sparse(index, grad, weight)
            return
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def _update_row_sparse(self, index, grad, weight):
        """Lazy row-sparse update: gather the touched rows of weight and
        state, run the ordinary dense optimizer kernel on that row block,
        scatter back. One mechanism covers every optimizer — the reference
        hand-writes per-optimizer sparse kernels (sgd/adam/ftrl *_update
        sparse paths); here the gather/scatter is an XLA program."""
        import jax.numpy as jnp

        from ..ndarray.ndarray import NDArray

        rows = grad.indices._data.astype(jnp.int32)
        state = self.states[index]

        def gather(s):
            if s is None:
                return None
            if isinstance(s, (tuple, list)):
                return type(s)(gather(x) for x in s)
            return NDArray(s._data[rows], s._ctx)

        def scatter(s, sr):
            if s is None:
                return
            if isinstance(s, (tuple, list)):
                for x, xr in zip(s, sr):
                    scatter(x, xr)
                return
            s._set_data(s._data.at[rows].set(sr._data))

        w_rows = NDArray(weight._data[rows], weight._ctx)
        state_rows = gather(state)
        self.optimizer.update_multi_precision(index, w_rows, grad.data,
                                              state_rows)
        weight._set_data(weight._data.at[rows].set(w_rows._data))
        scatter(state, state_rows)

    # reserved (non-index) key carrying the optimizer's per-index update
    # counts, so a resumed Adam/FTML-style run replays the same bias
    # correction t as the uninterrupted one (bitwise kill-resume); blobs
    # written before this key existed still load (counts then restart,
    # the old behavior)
    _COUNTS_KEY = "__update_counts__"

    def set_states(self, states):
        def _to_nd(x):
            if isinstance(x, _np.ndarray):
                from ..ndarray.ndarray import array

                return array(x)
            if isinstance(x, tuple):
                return tuple(_to_nd(y) for y in x)
            return x

        data = pickle.loads(states)
        counts = data.pop(self._COUNTS_KEY, None)
        self.states = {k: _to_nd(v) for k, v in data.items()}
        self.states_synced = {k: True for k in self.states}
        if counts is not None:
            self.optimizer._index_update_count = dict(counts)
            self.optimizer.num_update = max(
                [self.optimizer.begin_num_update, *counts.values()])

    def get_states(self, dump_optimizer=False):
        def _to_np(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, tuple):
                return tuple(_to_np(y) for y in x)
            return x

        out = {k: _to_np(v) for k, v in self.states.items()}
        counts = self.optimizer._index_update_count
        if counts:
            out[self._COUNTS_KEY] = dict(counts)
        return pickle.dumps(out)


def get_updater(optimizer):
    return Updater(optimizer)
