from .optimizer import (Optimizer, SGD, NAG, Adam, AdamW, AdaGrad, AdaDelta,
                        RMSProp, Ftrl, Adamax, Nadam, Signum, SGLD, DCASGD,
                        FTML, LAMB, LARS, LBSGD, Test, Updater, get_updater,
                        create, register)

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "AdaDelta",
           "RMSProp", "Ftrl", "Adamax", "Nadam", "Signum", "SGLD", "DCASGD",
           "FTML", "LAMB", "LARS", "LBSGD", "Test", "Updater", "get_updater",
           "create", "register"]
