"""RecordIO file format.

Parity: python/mxnet/recordio.py (MXRecordIO :37, MXIndexedRecordIO :216,
IRHeader pack/unpack :344-387) and the dmlc-core RecordIO writer the C++
side used. Binary format is byte-compatible with the reference:
each record = [kMagic u32][cflag:3bits|length:29bits u32][payload][pad to 4B].
A C++ reader for the hot data path lives in src/io (ctypes-loaded); this
module is the pure-Python contract + fallback.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
import zlib
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "IndexEntry",
           "RecordCorruptError", "load_index", "read_record_at", "pack",
           "unpack", "pack_img", "unpack_img"]

_kMagic = 0xced7230a


def _encode_flag_len(cflag, length):
    return (cflag << 29) | length


def _decode_flag_len(v):
    return v >> 29, v & ((1 << 29) - 1)


def read_logical_record(fileobj):
    """Read one logical record from `fileobj` at its current position.

    Handles split records (cflag kBegin=1/kMiddle=2/kEnd=3, produced when a
    payload contains the magic word): chunks are re-joined with the magic
    word re-inserted at each seam, matching the dmlc-core reader. Returns
    None at EOF. This is THE framing parser — the data pipeline
    (io/record_pipeline.py) delegates here; src/io/record_pipeline.cc
    mirrors the same rules natively.
    """
    chunks = None
    while True:
        hdr = fileobj.read(8)
        if len(hdr) < 8:
            if chunks is not None:
                raise ValueError("truncated split record")
            return None
        magic, fl = struct.unpack("<II", hdr)
        if magic != _kMagic:
            raise ValueError("invalid record magic")
        cflag, length = _decode_flag_len(fl)
        buf = fileobj.read(length)
        pad = (-length) % 4
        if pad:
            fileobj.read(pad)
        if chunks is None:
            if cflag == 0:
                return buf
            if cflag != 1:
                raise ValueError(f"unexpected continuation flag {cflag}")
            chunks = [buf]
        else:
            if cflag not in (2, 3):
                raise ValueError(f"unexpected record flag {cflag}")
            chunks.append(buf)
            if cflag == 3:
                return struct.pack("<I", _kMagic).join(chunks)


class MXRecordIO:
    """Reads/writes sequential RecordIO files (recordio.py:37)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.record = None
        self.flag = flag
        self.pid = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        del d["record"]
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        is_open = d.get("is_open", False)
        self.is_open = False
        self.record = None
        if is_open:
            self.open()

    def _check_pid(self, allow_reset=False):
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in multiple processes")

    def close(self):
        if not self.is_open:
            return
        self.record.close()
        self.is_open = False
        self.pid = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid(allow_reset=False)
        self.record.write(struct.pack("<II", _kMagic,
                                      _encode_flag_len(0, len(buf))))
        self.record.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        """Read one logical record (split records re-joined; see
        read_logical_record)."""
        assert not self.writable
        self._check_pid(allow_reset=True)
        return read_logical_record(self.record)

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with random access by key (recordio.py:216)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in iter(fin.readline, ""):
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        self.fidx = open(self.idx_path, "w") if self.writable else None

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        pos = self.idx[idx]
        self.record.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        # extended index line: offset + logical payload length + CRC32,
        # so streaming consumers (io/stream.py) can range-read and
        # integrity-check each record without scanning the record
        # stream. Legacy readers (this class, the native pipeline)
        # parse the first two columns only, so the format stays
        # backward compatible (docs/data.md).
        self.fidx.write(f"{key}\t{pos}\t{len(buf)}\t"
                        f"{zlib.crc32(buf) & 0xFFFFFFFF}\n")
        self.idx[key] = pos
        self.keys.append(key)


# ------------------------------------------------------------ offset index

IndexEntry = namedtuple("IndexEntry", ["key", "offset", "length", "crc32"])
IndexEntry.__doc__ = """One parsed line of a ``.idx`` offset index.

``length`` (logical payload bytes) and ``crc32`` (payload checksum) come
from the extended 4-column form ``tools/im2rec.py`` / ``write_idx``
emit; both are None for legacy 2-column indexes."""


class RecordCorruptError(ValueError):
    """A record failed its integrity check on read (CRC32/length recorded
    in the offset index, or an unreadable frame at the indexed offset).
    Structured: ``path`` / ``key`` / ``offset`` name the damaged record so
    recovery tooling and the ``io_records_corrupt`` skip policy
    (io/stream.py, docs/data.md) can report precisely what was lost."""

    def __init__(self, message, path=None, key=None, offset=None):
        super().__init__(message)
        self.path = path
        self.key = key
        self.offset = offset


def load_index(path, key_type=int):
    """Parse a RecordIO offset index into ``[IndexEntry]`` (file order).

    Accepts both the legacy 2-column ``key\\toffset`` form and the
    extended 4-column ``key\\toffset\\tlength\\tcrc32`` form the repo's
    writers emit; extra columns beyond the first are ignored by the
    legacy readers, so one file serves both."""
    entries = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 2:
                continue
            entries.append(IndexEntry(
                key_type(parts[0]), int(parts[1]),
                int(parts[2]) if len(parts) > 2 else None,
                int(parts[3]) if len(parts) > 3 else None))
    return entries


def read_record_at(fileobj, entry, path=None, verify=True):
    """Range-read the logical record indexed by ``entry`` and verify its
    payload against the index's recorded length and CRC32 (when
    present). This is THE verified-read primitive the streaming
    ingestion layer (io/stream.py) is built on: no full-file scan, and
    a damaged record surfaces as a structured :class:`RecordCorruptError`
    — never as garbage bytes silently decoded into a batch.

    The ``record_corrupt`` fault kind (resilience.faults) injects a bit
    flip here, between the read and the verification, so the chaos
    drill exercises the real detection path."""
    fileobj.seek(entry.offset)
    try:
        buf = read_logical_record(fileobj)
    except ValueError as e:
        raise RecordCorruptError(
            f"unreadable record frame at offset {entry.offset} of {path} "
            f"({e})", path=path, key=entry.key, offset=entry.offset) from e
    if buf is None:
        raise RecordCorruptError(
            f"no record at offset {entry.offset} of {path} (stale index?)",
            path=path, key=entry.key, offset=entry.offset)
    from .resilience import faults as _faults

    buf = _faults.maybe_corrupt_record(buf)
    if not verify:
        return buf
    if entry.length is not None and len(buf) != entry.length:
        raise RecordCorruptError(
            f"record {entry.key} at offset {entry.offset} of {path} is "
            f"{len(buf)} bytes but the index records {entry.length}",
            path=path, key=entry.key, offset=entry.offset)
    if entry.crc32 is not None:
        crc = zlib.crc32(buf) & 0xFFFFFFFF
        if crc != entry.crc32:
            raise RecordCorruptError(
                f"record {entry.key} at offset {entry.offset} of {path} "
                f"failed its CRC32 integrity check (index records "
                f"{entry.crc32:#010x}, payload hashes {crc:#010x})",
                path=path, key=entry.key, offset=entry.offset)
    return buf


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Packs a string payload with an IRHeader (recordio.py:344)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
        buf = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        buf = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                          header.id2) + label.tobytes()
    return buf + s


def unpack(s):
    """Unpacks an IRHeader + payload (recordio.py:365)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=1):
    """Unpacks a record into header + decoded image (recordio.py:379)."""
    header, s = unpack(s)
    img = _imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Packs an image with an IRHeader (recordio.py:387)."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def _imdecode(buf, iscolor=1):
    from io import BytesIO

    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("image decode requires PIL") from e
    img = Image.open(BytesIO(buf.tobytes()))
    if iscolor == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
    return arr


def _imencode(img, quality=95, img_fmt=".jpg"):
    from io import BytesIO

    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("image encode requires PIL") from e
    if hasattr(img, "asnumpy"):
        img = img.asnumpy()
    img = np.asarray(img).astype(np.uint8)
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[:, :, 0]
    pil = Image.fromarray(img)
    bio = BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(bio, format=fmt, quality=quality)
    return bio.getvalue()
