"""AttrScope — scoped attributes for symbol construction.

Capability parity with python/mxnet/attribute.py (AttrScope :28) and its
uses: `with mx.AttrScope(ctx_group='stage1', lr_mult='0.1'):` stamps every
node created in the scope. `ctx_group` + `bind(group2ctx=...)` gives the
reference's manual model-parallel placement (executor.py resolves groups
to jax devices and inserts cross-device transfers); it also no longer
solely drives manual
device placement (GSPMD shardings do — SURVEY.md §2.3 model parallelism
row); the attrs still flow to `Symbol.attr_dict()` where
`Module.init_optimizer` consumes `__lr_mult__`/`__wd_mult__`, and
`ctx_group` remains available to sharding-rule authors as a grouping tag.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


class AttrScope:
    """Attribute manager (attribute.py:28): attrs apply to every symbol
    node created inside the scope; nested scopes merge (inner wins)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError(
                    "AttrScope values must be strings (reference contract); "
                    f"got {type(v).__name__}")
        self._attrs = {f"__{k}__" if not k.startswith("__") else k: v
                       for k, v in kwargs.items()}

    def __enter__(self):
        _stack().append(self._attrs)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def current_attrs():
    """Merged attrs of all active scopes (outer to inner)."""
    merged = {}
    for attrs in _stack():
        merged.update(attrs)
    return merged
