"""Symbol — declarative graph composition compiling to one XLA executable.

Parity: python/mxnet/symbol/symbol.py + the nnvm graph substrate
(src/nnvm/, src/executor/). TPU-native redesign: the Symbol DAG is a thin
Python structure over the same op registry the imperative path uses; binding
traces the whole graph once into a jitted function — the "XLA-HLO emission
pass" the north star asks for. nnvm passes map as follows: shape/type
inference = fixpoint propagation + jax.eval_shape; MXGradient = jax.vjp at
bind time; PlanMemory / DetectInplaceAddTo / pointwise fusion = XLA buffer
assignment + fusion (nothing to build).
"""
from __future__ import annotations

import inspect as _inspect
import json
import threading

import numpy as _np

from ..base import MXNetError
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "name_manager"]

_NAME_LOCK = threading.Lock()
_NAME_COUNTERS: dict[str, int] = {}


def _auto_name(kind):
    with _NAME_LOCK:
        i = _NAME_COUNTERS.get(kind, 0)
        _NAME_COUNTERS[kind] = i + 1
    return f"{kind}{i}"


import itertools as _itertools

from ..attribute import current_attrs as _current_attrs

_node_serial = _itertools.count()


def node_serial_watermark():
    """Current creation-order watermark; nodes created after this call have
    serial >= the returned value (used by symbol.contrib subgraph cutting)."""
    return next(_node_serial)


class _Node:
    """One graph node: a variable or an op application."""

    __slots__ = ("op", "name", "params", "inputs", "attrs", "aux_mark",
                 "serial")

    def __init__(self, op, name, params=None, inputs=None, attrs=None):
        self.op = op              # None for variables, else canonical op name
        self.name = name
        self.params = params or {}
        self.inputs = inputs or []  # list[(Node, out_idx)]
        self.attrs = {**_current_attrs(), **(attrs or {})}
        self.aux_mark = False     # variable used in a mutate slot => aux state
        self.serial = next(_node_serial)  # creation order (subgraph cutting)

    @property
    def is_var(self):
        return self.op is None

    def num_outputs(self):
        if self.is_var:
            return 1
        op = _registry.get_op(self.op)
        return op.n_out(op.normalize(self.params))


class Symbol:
    """A handle to one or more output entries of the graph."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(Node, idx)]

    # ------------------------------------------------------------- structure
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return ", ".join(n.name for n, _ in self._outputs)

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield Symbol([self._outputs[i]])

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(f"no output named {index!r}: {names}")
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def _topo_nodes(self):
        order, seen = [], set()

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for (inp, _) in node.inputs:
                visit(inp)
            order.append(node)

        for n, _ in self._outputs:
            visit(n)
        return order

    def list_arguments(self):
        return [n.name for n in self._topo_nodes() if n.is_var and not n.aux_mark]

    def list_auxiliary_states(self):
        return [n.name for n in self._topo_nodes() if n.is_var and n.aux_mark]

    def list_outputs(self):
        out = []
        for n, i in self._outputs:
            if n.num_outputs() > 1:
                out.append(f"{n.name}_output{i}")
            else:
                out.append(f"{n.name}_output" if not n.is_var else n.name)
        return out

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_var]

    def get_internals(self):
        entries = []
        for n in self._topo_nodes():
            for i in range(n.num_outputs()):
                entries.append((n, i))
        return Symbol(entries)

    def get_children(self):
        nodes = {id(n): n for n, _ in self._outputs}
        ins = []
        for n, _ in self._outputs:
            ins.extend(n.inputs)
        return Symbol(ins) if ins else None

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    def attr_dict(self):
        return {n.name: dict(n.attrs) for n in self._topo_nodes() if n.attrs}

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(kwargs)

    # ---------------------------------------------------------- composition
    def __call__(self, *args, **kwargs):
        """Compose: replace variable inputs by other symbols."""
        s = Symbol(self._outputs)
        # compose by name
        var_nodes = {n.name: n for n in s._topo_nodes() if n.is_var}
        for name, sub in kwargs.items():
            if name in var_nodes and isinstance(sub, Symbol):
                node = var_nodes[name]
                node.op = "identity"
                node.inputs = [sub._outputs[0]]
        return s

    def _binary(self, other, opname, reverse=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if reverse else (self, other)
            return _create(opname, [lhs, rhs], {})
        return _create(opname + "_scalar", [self],
                       {"scalar": float(other), "reverse": reverse})

    def __add__(self, o):
        return self._binary(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elemwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elemwise_div", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "elemwise_pow")

    def __neg__(self):
        return _create("negative", [self], {})

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal")

    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "broadcast_equal")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "broadcast_not_equal")
        return NotImplemented

    def __hash__(self):
        return id(self)

    # convenience mirrors of common ops (full set via generated sym.* wrappers)
    def reshape(self, shape=None, **kw):
        return _create("Reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return _create("transpose", [self], {"axes": tuple(axes) if axes else None})

    def sum(self, axis=None, keepdims=False):
        return _create("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _create("mean", [self], {"axis": axis, "keepdims": keepdims})

    def flatten(self):
        return _create("Flatten", [self], {})

    def slice_axis(self, axis=0, begin=0, end=None):
        return _create("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def expand_dims(self, axis):
        return _create("expand_dims", [self], {"axis": axis})

    def astype(self, dtype):
        return _create("Cast", [self], {"dtype": str(dtype)})

    def softmax(self, axis=-1):
        return _create("softmax", [self], {"axis": axis})

    # ------------------------------------------------------------- inference
    def infer_shape(self, **kwargs):
        try:
            return self._infer_shape_impl(partial=False, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, **kwargs):
        return self._infer_shape_impl(partial=True, **kwargs)

    def _infer_shape_impl(self, partial=False, **kwargs):
        """Fixpoint shape propagation. Forward: jax.eval_shape when all inputs
        known. Parameter shapes: per-op hooks (the TPU stand-in for
        FInferShape backward inference, infer_graph_attr_pass.cc:553)."""
        known = self._propagate_shapes(kwargs)
        nodes = self._topo_nodes()
        arg_shapes = []
        for name in self.list_arguments():
            node = next(x for x in nodes if x.is_var and x.name == name)
            s = known.get((id(node), 0))
            if s is None and not partial:
                raise MXNetError(f"infer_shape: cannot infer shape of argument "
                                 f"'{name}' — provide it explicitly")
            arg_shapes.append(s)
        out_shapes = [known.get((id(n), i)) for n, i in self._outputs]
        aux_shapes = []
        for name in self.list_auxiliary_states():
            node = next(x for x in nodes if x.is_var and x.name == name)
            aux_shapes.append(known.get((id(node), 0)))
        return arg_shapes, out_shapes, aux_shapes

    def _propagate_shapes(self, kwargs):
        """Run fixpoint shape propagation; return the full per-node map
        {(id(node), slot): shape}. Shared by infer_shape and exporters
        (e.g. ONNX) that need internal value shapes."""
        known: dict[tuple, tuple] = {}
        nodes = self._topo_nodes()
        for n in nodes:
            if n.is_var and n.name in kwargs and kwargs[n.name] is not None:
                known[(id(n), 0)] = tuple(kwargs[n.name])
        changed = True
        while changed:
            changed = False
            for n in nodes:
                if n.is_var:
                    continue
                in_shapes = [known.get((id(i), s)) for i, s in n.inputs]
                op = _registry.get_op(n.op)
                params = op.normalize(n.params)
                hook = _PARAM_SHAPE_HOOKS.get(op.name)
                if hook and any(s is None for s in in_shapes):
                    hints = hook(in_shapes, params)
                    for idx, shape in (hints or {}).items():
                        node_i, slot_i = n.inputs[idx]
                        if shape is not None and known.get((id(node_i), slot_i)) is None:
                            known[(id(node_i), slot_i)] = tuple(shape)
                            changed = True
                    in_shapes = [known.get((id(i), s)) for i, s in n.inputs]
                if all(s is not None for s in in_shapes) and \
                        known.get((id(n), 0)) is None:
                    out_shapes = _eval_out_shapes(n, in_shapes)
                    for i, s in enumerate(out_shapes):
                        known[(id(n), i)] = s
                    changed = True
                # element-shaped ops propagate a known OUTPUT shape back to
                # their primary input (lets parameter hooks see through
                # quantize/dequantize pairs to the weight variable)
                if op.name in _SHAPE_PASSTHROUGH and \
                        known.get((id(n), 0)) is not None and n.inputs:
                    node_i, slot_i = n.inputs[0]
                    if known.get((id(node_i), slot_i)) is None:
                        known[(id(node_i), slot_i)] = known[(id(n), 0)]
                        changed = True
        return known

    def infer_type(self, **kwargs):
        arg_names = self.list_arguments()
        dt = kwargs.get(arg_names[0], _np.float32) if arg_names else _np.float32
        return ([_np.dtype(dt)] * len(arg_names),
                [_np.dtype(dt)] * len(self._outputs),
                [_np.dtype(dt)] * len(self.list_auxiliary_states()))

    # --------------------------------------------------------------- binding
    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor

        return Executor._simple_bind(self, ctx, grad_req=grad_req,
                                     group2ctx=group2ctx, **kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def gradient(self, wrt):
        raise MXNetError("symbol.gradient: use bind + backward")

    # ---------------------------------------------------------- (de)serialize
    def tojson(self):
        nodes = self._topo_nodes()
        idx_of = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_var else n.op,
                "name": n.name,
                "attrs": {k: json.dumps(v) for k, v in n.params.items()} if n.params else {},
                "inputs": [[idx_of[id(i)], s, 0] for i, s in n.inputs],
                "aux": n.aux_mark,
            })
        heads = [[idx_of[id(n)], i, 0] for n, i in self._outputs]
        return json.dumps({"nodes": jnodes, "heads": heads,
                           "mxnet_tpu_version": 1}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def debug_str(self):
        lines = []
        for n in self._topo_nodes():
            kind = "Variable" if n.is_var else n.op
            ins = ", ".join(i.name for i, _ in n.inputs)
            lines.append(f"{kind} {n.name}({ins})")
        return "\n".join(lines)


def _eval_out_shapes(node, in_shapes):
    import jax
    import jax.numpy as jnp

    op = _registry.get_op(node.op)
    params = op.normalize(node.params)
    fn = op.closed(params)
    specs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]
    try:
        out = jax.eval_shape(fn, *specs)
    except Exception as e:
        raise MXNetError(f"shape inference failed at node '{node.name}' "
                         f"(op {node.op}, inputs {in_shapes}): {e}") from e
    outs = out if isinstance(out, tuple) else (out,)
    return [tuple(o.shape) for o in outs]


# --- parameter-shape hooks (backward inference for learnable params) --------

def _fc_hook(in_shapes, p):
    data = in_shapes[0]
    hints = {}
    if data is not None:
        import numpy as np

        in_dim = int(np.prod(data[1:])) if p.get("flatten", True) else data[-1]
        nh = p["num_hidden"]
        hints[1] = (nh, in_dim)
        if len(in_shapes) > 2:
            hints[2] = (nh,)
    return hints


def _conv_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    k = p.get("kernel") or ()
    k = (k,) if isinstance(k, int) else tuple(k)
    nf = p["num_filter"]
    ng = p.get("num_group", 1)
    layout = p.get("layout")
    if layout and layout[1] != "C":  # channels-last: OHWI weights
        hints = {1: (nf,) + k + (data[-1] // ng,)}
    else:
        hints = {1: (nf, data[1] // ng) + k}
    if len(in_shapes) > 2:
        hints[2] = (nf,)
    return hints


def _deconv_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    k = tuple(p.get("kernel") or ())
    nf = p["num_filter"]
    ng = p.get("num_group", 1)
    hints = {1: (data[1], nf // ng) + k}
    if len(in_shapes) > 2:
        hints[2] = (nf,)
    return hints


def _bn_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    c = data[p.get("axis", 1)]
    return {i: (c,) for i in range(1, 5)}


def _norm_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    ax = p.get("axis", -1)
    c = data[ax]
    return {1: (c,), 2: (c,)}


def _groupnorm_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    return {1: (data[1],), 2: (data[1],)}


def _embedding_hook(in_shapes, p):
    return {1: (p["input_dim"], p["output_dim"])}


def _rnn_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    from ..ops.rnn import _GATES

    T, N, I = data
    H = p["state_size"]
    L = p.get("num_layers", 1)
    D = 2 if p.get("bidirectional") else 1
    g = _GATES[p.get("mode", "lstm")]
    size = 0
    for layer in range(L):
        in_sz = I if layer == 0 else H * D
        size += D * (g * H * in_sz + g * H * H)
    size += L * D * 2 * g * H
    hints = {1: (size,), 2: (L * D, N, H)}
    if len(in_shapes) > 3:
        hints[3] = (L * D, N, H)
    return hints


def _softmax_output_hook(in_shapes, p):
    # label shape from data shape (reference SoftmaxOutputShape,
    # softmax_output.cc): (N,) default, (N, d1...) for multi_output over
    # the channel axis, data.shape[:-1] under preserve_shape. Lets deploy
    # graphs that kept their training head bind without an explicit
    # label shape (the c_predict_api contract).
    data = in_shapes[0]
    if data is None:
        return {}
    if p.get("multi_output"):
        return {1: (data[0],) + tuple(data[2:])}
    if p.get("preserve_shape"):
        return {1: tuple(data[:-1])}
    return {1: (data[0],)}


def _regression_output_hook(in_shapes, p):
    data = in_shapes[0]
    if data is None:
        return {}
    return {1: tuple(data)}


_PARAM_SHAPE_HOOKS = {
    "FullyConnected": _fc_hook,
    "Convolution": _conv_hook,
    "Deconvolution": _deconv_hook,
    "BatchNorm": _bn_hook,
    "LayerNorm": _norm_hook,
    "GroupNorm": _groupnorm_hook,
    "InstanceNorm": _groupnorm_hook,
    "Embedding": _embedding_hook,
    "RNN": _rnn_hook,
    "SoftmaxOutput": _softmax_output_hook,
    "LinearRegressionOutput": _regression_output_hook,
    "LogisticRegressionOutput": _regression_output_hook,
    "MAERegressionOutput": _regression_output_hook,
}

# ops whose primary output shape equals their primary input shape; a known
# output back-propagates to the input during fixpoint inference
_SHAPE_PASSTHROUGH = {
    "_contrib_quantize_v2", "_contrib_dequantize", "amp_cast", "Cast",
    "identity", "BlockGrad",
}


# ------------------------------------------------------------- construction

def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        attrs["__init__"] = str(init)
    node = _Node(None, name, attrs=attrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _create(opname, input_syms, params, name=None, attr=None):
    """Create an op node; auto-create missing parameter variables the way the
    reference does (generated creators add <name>_weight etc.)."""
    op = _registry.get_op(opname)
    name = name or _auto_name(op.name.lower().replace("_", ""))
    inputs = []
    for s in input_syms:
        if s is None:
            continue
        if len(s._outputs) != 1:
            raise MXNetError(f"{opname}: cannot take a multi-output symbol "
                             f"as a single input")
        inputs.append(s._outputs[0])
    node = _Node(op.name, name, params=dict(params), inputs=inputs,
                 attrs=dict(attr or {}))
    return Symbol([(node, i) for i in range(node.num_outputs())])


def _array_param_names(op):
    """Leading positional (array) parameter names of the op function."""
    sig = _inspect.signature(op.fn)
    names = []
    for p in sig.parameters.values():
        if p.kind in (p.VAR_POSITIONAL,):
            return names, True
        if p.default is p.empty or p.name in ("bias", "state_cell", "rng_key",
                                              "sequence_length", "like",
                                              "trans"):
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY):
                names.append(p.name)
        else:
            break
    return names, False


def make_symbol_creator(opname):
    op = _registry.get_op(opname)
    arr_names, variadic = _array_param_names(op)

    def creator(*args, name=None, attr=None, **kwargs):
        syms = []
        rest = []
        for a in args:
            if isinstance(a, Symbol):
                syms.append(a)
            else:
                rest.append(a)
        name = name or _auto_name(op.name.lower().replace("_", ""))
        if variadic:
            params = dict(kwargs)
            params.pop("num_args", None)
            return _create(opname, syms, params, name=name, attr=attr)
        # map keyword-symbol args (e.g. data=..., weight=...)
        slots: dict[str, Symbol | None] = {}
        si = 0
        for an in arr_names:
            if an in kwargs and isinstance(kwargs[an], Symbol):
                slots[an] = kwargs.pop(an)
            elif si < len(syms):
                slots[an] = syms[si]
                si += 1
            else:
                slots[an] = None
        params = dict(kwargs)
        # positional non-symbol args map onto remaining op params (rare)
        # auto-create missing parameter variables
        mutate_idx = set(op.mutate) if not callable(op.mutate) else set()
        final_inputs = []
        for idx, an in enumerate(arr_names):
            s = slots[an]
            if s is None:
                if an in ("bias",) and params.get("no_bias"):
                    continue
                if an == "trans" and params.get("no_trans"):
                    continue
                if an == "rng_key":
                    s = Variable(f"{name}_rng_key")
                    s._outputs[0][0].aux_mark = True
                elif an in ("state_cell",) and params.get("mode") != "lstm":
                    continue
                elif an in ("sequence_length", "like", "label"):
                    continue
                else:
                    s = Variable(f"{name}_{an}")
                    if idx in mutate_idx:
                        s._outputs[0][0].aux_mark = True
            elif idx in mutate_idx:
                # explicitly-passed bare variables in mutate slots are
                # auxiliary state too (reference: mutable inputs are aux)
                node = s._outputs[0][0]
                if node.is_var:
                    node.aux_mark = True
            final_inputs.append(s)
        return _create(opname, final_inputs, params, name=name, attr=attr)

    creator.__name__ = opname
    creator.__doc__ = op.doc
    return creator


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _parse_ref_attr(value):
    """One reference-JSON attr string -> Python value. The reference
    serializes every op param as a string ("64", "(3, 3)", "True",
    "relu"); literal forms parse, everything else stays a string."""
    import ast

    if not isinstance(value, str):
        return tuple(value) if isinstance(value, list) else value
    try:
        v = ast.literal_eval(value)
    except (ValueError, SyntaxError):
        return value
    return tuple(v) if isinstance(v, list) else v


def _node_attr_dict(jn):
    """Per-node attribute dict across reference vintages: 1.x "attrs",
    0.x "attr"/"param"."""
    for key in ("attrs", "attr", "param"):
        if jn.get(key):
            return jn[key]
    return {}


def _entry(e):
    """Graph entry [node_id, out_index(, version)] -> (id, index)."""
    return (e[0], e[1] if len(e) > 1 else 0)


def _load_reference_json(data):
    """Import a reference-saved Symbol JSON (python/mxnet symbol.save /
    nnvm::Graph SaveJSON: "arg_nodes" + "node_row_ptr" + stringly-typed
    attrs). Auxiliary states are not tagged in the reference format —
    they are recovered from the op registry's mutate slots, the same
    declaration the creator path uses."""
    nodes = []
    for jn in data["nodes"]:
        attrs = {k: _parse_ref_attr(v) for k, v in _node_attr_dict(jn).items()}
        if jn["op"] == "null":
            node = _Node(None, jn["name"],
                         attrs={k: v for k, v in attrs.items()
                                if k.startswith("__")})
        else:
            params = {k: v for k, v in attrs.items()
                      if not k.startswith("__")}
            node = _Node(jn["op"], jn["name"], params=params,
                         attrs={k: v for k, v in attrs.items()
                                if k.startswith("__")})
        node.inputs = [(nodes[i], s) for i, s in map(_entry, jn["inputs"])]
        nodes.append(node)
    for n in nodes:
        if n.is_var:
            continue
        op = _registry.get_op(n.op)
        for slot in op.mutate_slots(op.normalize(n.params)):
            if slot < len(n.inputs):
                tgt, _ = n.inputs[slot]
                if tgt.is_var:
                    tgt.aux_mark = True
    return Symbol([(nodes[i], s) for i, s in map(_entry, data["heads"])])


def load_json(json_str):
    data = json.loads(json_str)
    if "arg_nodes" in data or "node_row_ptr" in data:
        return _load_reference_json(data)
    nodes = []
    for jn in data["nodes"]:
        params = {k: json.loads(v) for k, v in jn.get("attrs", {}).items()}
        params = {k: (tuple(v) if isinstance(v, list) else v) for k, v in params.items()}
        if jn["op"] == "null":
            node = _Node(None, jn["name"])
            node.aux_mark = jn.get("aux", False)
        else:
            node = _Node(jn["op"], jn["name"], params=params)
        node.inputs = [(nodes[i], s) for i, s, _ in jn["inputs"]]
        nodes.append(node)
    return Symbol([(nodes[i], s) for i, s, _ in data["heads"]])
