"""mx.sym — symbolic namespace with generated op creators.

Parity: python/mxnet/symbol/ (creators generated from the op registry at
import, like register.py does from the C API).
"""
from __future__ import annotations

import sys as _sys

from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     make_symbol_creator)
from ..ops.registry import list_ops as _list_ops, _ALIASES as _OP_ALIASES

_MODULE = _sys.modules[__name__]


def _populate():
    for name in _list_ops():
        if not hasattr(_MODULE, name):
            setattr(_MODULE, name, make_symbol_creator(name))
    for alias, canon in _OP_ALIASES.items():
        if alias.isidentifier() and not hasattr(_MODULE, alias):
            setattr(_MODULE, alias, make_symbol_creator(canon))


_populate()


def __getattr__(name):
    if name == "contrib":
        import importlib

        mod = importlib.import_module(".contrib", __name__)
        setattr(_MODULE, "contrib", mod)
        return mod
    from ..ops.registry import get_op

    try:
        get_op(name)
    except Exception:
        raise AttributeError(name)
    c = make_symbol_creator(name)
    setattr(_MODULE, name, c)
    return c
