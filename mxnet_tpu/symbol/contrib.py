"""mx.sym.contrib — short names for `_contrib_*` registered ops.

Parity: python/mxnet/symbol/contrib.py (generated from `_contrib_`-prefixed
op names).
"""
from __future__ import annotations

import sys as _sys

_MODULE = _sys.modules[__name__]
_PREFIX = "_contrib_"


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from ..ops.registry import get_op
    from .symbol import make_symbol_creator

    for candidate in (_PREFIX + name, name):
        try:
            get_op(candidate)
        except Exception:
            continue
        c = make_symbol_creator(candidate)
        setattr(_MODULE, name, c)
        return c
    raise AttributeError(name)


def __dir__():
    from ..ops.registry import list_ops

    return sorted(n[len(_PREFIX):] for n in list_ops()
                  if n.startswith(_PREFIX))
