"""mx.sym.contrib — short names for `_contrib_*` registered ops, plus the
symbolic control-flow builders (foreach / while_loop / cond).

Parity: python/mxnet/symbol/contrib.py (generated `_contrib_` creators; the
control-flow builders mirror its foreach :136, while_loop :276, cond :425 —
subgraph construction via placeholder variables, free-variable capture from
the enclosing scope). Execution lowers to lax.scan/while_loop/cond through
ops/control_flow.py.
"""
from __future__ import annotations

import sys as _sys

from ..base import MXNetError

_MODULE = _sys.modules[__name__]
_PREFIX = "_contrib_"


from ..base import listify as _listify  # noqa: E402  (shared contract)


def _cut_subgraph(group_sym, boundary, name):
    """Cut the subgraph at pre-existing computed nodes.

    Any edge from a node created inside the control-flow body (serial >=
    boundary) to a pre-boundary *computed* node is replaced by a fresh
    placeholder Variable; the outer value is evaluated once in the
    enclosing graph and fed in as a loop input (the reference's
    _cut_subgraph does the same for captured symbols). Pre-boundary
    Variables are left in place — they become ordinary free arguments and
    keep weight sharing by name. Mutates `group_sym` in place and returns
    {placeholder_name: outer_ref_symbol}.
    """
    from .symbol import Symbol, Variable

    cut_map = {}   # (id(node), slot) -> (var_node, outer_ref)

    def cut_edge(inode, islot):
        key = (id(inode), islot)
        if key not in cut_map:
            v = Variable(f"{name}_cut{len(cut_map)}")
            cut_map[key] = (v._outputs[0][0], Symbol([(inode, islot)]))
        return cut_map[key][0]

    # outputs that point straight at outer computation get cut too
    new_outputs = []
    for node, slot in group_sym._outputs:
        if node.serial < boundary and not node.is_var:
            new_outputs.append((cut_edge(node, slot), 0))
        else:
            new_outputs.append((node, slot))
    group_sym._outputs = new_outputs

    seen = set()
    stack = [n for n, _ in group_sym._outputs]
    while stack:
        node = stack.pop()
        if id(node) in seen or node.serial < boundary:
            continue
        seen.add(id(node))
        for k, (inode, islot) in enumerate(list(node.inputs)):
            if inode.serial < boundary and not inode.is_var:
                node.inputs[k] = (cut_edge(inode, islot), 0)
            elif inode.serial >= boundary:
                stack.append(inode)
    return {vn.name: ref for vn, ref in cut_map.values()}


def _subgraph_program(group_sym):
    """Trace a subgraph Symbol into an interpreted program; returns
    (table_key, arg_names, var_nodes_by_name)."""
    from ..executor import _graph_program
    from ..ops.control_flow import stash_subgraph

    pure_fn, arg_names, aux_names, _ = _graph_program(group_sym)
    if aux_names:
        raise MXNetError(
            "control-flow subgraphs cannot mutate auxiliary state "
            f"(found {aux_names}); move stateful ops out of the loop body")
    var_nodes = {}
    for node in group_sym._topo_nodes():
        if node.is_var:
            var_nodes[node.name] = node
    key = stash_subgraph(pure_fn, len(arg_names))
    return key, arg_names, var_nodes


def _role_maps(arg_names, placeholder_names):
    """Split subgraph args into placeholder roles and free variables.

    Returns (maps, free_names): maps[role_name] = tuple of
    (argpos, role_idx); free_names = subgraph args that are not
    placeholders, in arg order.
    """
    name_to_role = {}
    for role, names in placeholder_names.items():
        for i, n in enumerate(names):
            name_to_role[n] = (role, i)
    maps = {role: [] for role in placeholder_names}
    free_names = []
    for pos, n in enumerate(arg_names):
        if n in name_to_role:
            role, i = name_to_role[n]
            maps[role].append((pos, i))
        else:
            free_names.append((pos, n))
    return {r: tuple(m) for r, m in maps.items()}, free_names


def _free_ref(n, var_nodes, cut_refs):
    from .symbol import Symbol

    return cut_refs.get(n) or Symbol([(var_nodes[n], 0)])


def foreach(body, data, init_states, name="foreach"):
    """Scan `body` over axis 0 of `data` (sym.contrib.foreach parity).

    body(data_slice, states) -> (step_outputs, next_states); returns
    (stacked_outputs, final_states) with the same nesting as the inputs.
    Lowers to one lax.scan (HLO While), not an unrolled graph.
    """
    from .symbol import (Symbol, Variable, _create, node_serial_watermark)

    boundary = node_serial_watermark()
    data_list, data_is_list = _listify(data)
    state_list, state_is_list = _listify(init_states)
    data_ph = [Variable(f"{name}_data{i}") for i in range(len(data_list))]
    state_ph = [Variable(f"{name}_state{i}") for i in range(len(state_list))]
    outs, out_states = body(
        data_ph if data_is_list else data_ph[0],
        state_ph if state_is_list else (state_ph[0] if state_ph else []))
    out_list, out_is_list = _listify(outs)
    out_state_list, _ = _listify(out_states)
    if len(out_state_list) != len(state_list):
        raise MXNetError("foreach body must return as many states as "
                         "init_states")
    from .symbol import Group

    sub = Group(out_list + out_state_list)
    cut_refs = _cut_subgraph(sub, boundary, name)
    key, arg_names, var_nodes = _subgraph_program(sub)
    maps, free = _role_maps(arg_names, {
        "data": [p._outputs[0][0].name for p in data_ph],
        "state": [p._outputs[0][0].name for p in state_ph],
    })
    params = {
        "_sub": key, "_n_data": len(data_list), "_n_state": len(state_list),
        "_n_out": len(out_list), "_data_map": maps["data"],
        "_state_map": maps["state"],
        "_free_map": tuple((pos, k) for k, (pos, _) in enumerate(free)),
    }
    inputs = (data_list + state_list +
              [_free_ref(n, var_nodes, cut_refs) for _, n in free])
    node_sym = _create("_foreach", inputs, params, name=name)
    n_out = len(out_list)
    outs_syms = [node_sym[i] for i in range(n_out)]
    state_syms = [node_sym[n_out + i] for i in range(len(state_list))]
    return (outs_syms if out_is_list else outs_syms[0],
            state_syms if state_is_list else
            (state_syms[0] if state_syms else []))


def while_loop(cond, func, loop_vars, max_iterations, name="while_loop"):
    """sym.contrib.while_loop parity: cond(*loop_vars) -> scalar;
    func(*loop_vars) -> (step_outputs, new_loop_vars). Step outputs are
    stacked into (max_iterations, ...) buffers (tail rows zero)."""
    from .symbol import (Group, Symbol, Variable, _create,
                         node_serial_watermark)

    boundary = node_serial_watermark()
    state_list, state_is_list = _listify(loop_vars)
    ph = [Variable(f"{name}_var{i}") for i in range(len(state_list))]
    ph_args = ph if state_is_list else ph[0]
    cond_out = cond(*ph) if state_is_list else cond(ph_args)
    outs, new_states = func(*ph) if state_is_list else func(ph_args)
    out_list, out_is_list = _listify(outs)
    new_state_list, _ = _listify(new_states)
    if len(new_state_list) != len(state_list):
        raise MXNetError("while_loop func must return as many loop_vars")

    ph_names = [p._outputs[0][0].name for p in ph]
    body_sub = Group(out_list + new_state_list)
    body_cuts = _cut_subgraph(body_sub, boundary, name + "_body")
    body_key, body_args, body_vars = _subgraph_program(body_sub)
    body_maps, body_free = _role_maps(body_args, {"state": ph_names})
    cond_sub = Group([cond_out])
    cond_cuts = _cut_subgraph(cond_sub, boundary, name + "_cond")
    cond_key, cond_args, cond_vars = _subgraph_program(cond_sub)
    cond_maps, cond_free = _role_maps(cond_args, {"state": ph_names})

    params = {
        "_cond_sub": cond_key, "_body_sub": body_key,
        "_n_state": len(state_list), "_n_body_free": len(body_free),
        "_n_out": len(out_list), "_max_iterations": int(max_iterations),
        "_body_state_map": body_maps["state"],
        "_body_free_map": tuple(
            (pos, k) for k, (pos, _) in enumerate(body_free)),
        "_cond_state_map": cond_maps["state"],
        "_cond_free_map": tuple(
            (pos, k) for k, (pos, _) in enumerate(cond_free)),
    }
    inputs = (state_list +
              [_free_ref(n, body_vars, body_cuts) for _, n in body_free] +
              [_free_ref(n, cond_vars, cond_cuts) for _, n in cond_free])
    node_sym = _create("_while_loop", inputs, params, name=name)
    n_out = len(out_list)
    outs_syms = [node_sym[i] for i in range(n_out)]
    state_syms = [node_sym[n_out + i] for i in range(len(state_list))]
    return (outs_syms if out_is_list else outs_syms[0],
            state_syms if state_is_list else state_syms[0])


def cond(pred, then_func, else_func, name="cond"):
    """sym.contrib.cond parity: `pred` is a scalar Symbol; then_func() and
    else_func() build branches with identical output structure."""
    from .symbol import Group, Symbol, _create, node_serial_watermark

    boundary = node_serial_watermark()
    then_out = then_func()
    else_out = else_func()
    then_list, then_is_list = _listify(then_out)
    else_list, _ = _listify(else_out)
    if len(then_list) != len(else_list):
        raise MXNetError("cond branches must have the same number of outputs")

    pred_sub = Group([pred])
    pred_cuts = _cut_subgraph(pred_sub, boundary, name + "_pred")
    pred_key, pred_args, pred_vars = _subgraph_program(pred_sub)
    then_sub = Group(then_list)
    then_cuts = _cut_subgraph(then_sub, boundary, name + "_then")
    then_key, then_args, then_vars = _subgraph_program(then_sub)
    else_sub = Group(else_list)
    else_cuts = _cut_subgraph(else_sub, boundary, name + "_else")
    else_key, else_args, else_vars = _subgraph_program(else_sub)

    inputs = []
    pred_map, then_map, else_map = [], [], []
    for argpos, n in enumerate(pred_args):
        pred_map.append((argpos, len(inputs)))
        inputs.append(_free_ref(n, pred_vars, pred_cuts))
    for argpos, n in enumerate(then_args):
        then_map.append((argpos, len(inputs)))
        inputs.append(_free_ref(n, then_vars, then_cuts))
    for argpos, n in enumerate(else_args):
        else_map.append((argpos, len(inputs)))
        inputs.append(_free_ref(n, else_vars, else_cuts))

    params = {
        "_pred_sub": pred_key, "_then_sub": then_key, "_else_sub": else_key,
        "_pred_map": tuple(pred_map), "_then_map": tuple(then_map),
        "_else_map": tuple(else_map), "_n_out": len(then_list),
    }
    node_sym = _create("_cond", inputs, params, name=name)
    outs = [node_sym[i] for i in range(len(then_list))]
    return outs if then_is_list else outs[0]


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from ..ops.registry import get_op
    from .symbol import make_symbol_creator

    for candidate in (_PREFIX + name, name):
        try:
            get_op(candidate)
        except Exception:
            continue
        c = make_symbol_creator(candidate)
        setattr(_MODULE, name, c)
        return c
    raise AttributeError(name)


def __dir__():
    from ..ops.registry import list_ops

    return sorted(n[len(_PREFIX):] for n in list_ops()
                  if n.startswith(_PREFIX))
