"""Fused multi-step RNN layers: RNN / LSTM / GRU.

Parity: python/mxnet/gluon/rnn/rnn_layer.py:307,404,535 — the reference
dispatches to the monolithic sym.RNN op (cuDNN); here the same RNN op is a
lax.scan program (ops/rnn.py) whose gate matmuls ride the MXU. Parameter
layout (flat vector packing) matches the reference so checkpoints
round-trip.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..parameter import Parameter
from ... import ndarray as nd

__all__ = ["RNN", "LSTM", "GRU", "_RNNLayer"]


class _RNNLayer(HybridBlock):
    """Base for fused RNN layers (rnn_layer.py:36)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        if projection_size:
            raise NotImplementedError(
                "projection_size (LSTMP) is not implemented yet on TPU")
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = f"{shape[1] if shape[1] else None} -> {shape[0] // self._gates}"
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _collect_params_with_prefix(self, prefix=""):
        # same contract as Block: .data() raises DeferredInitializationError
        # for params whose shapes are still pending
        if prefix:
            prefix += "."
        return {prefix + k: val.data() for k, val in self._reg_params.items()
                if val._data is not None or val._deferred_init}

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def cast(self, dtype):
        super().cast(dtype)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            info = dict(info)
            shape = info.pop("shape")
            if func is None:
                state = nd.zeros(shape, **{k: v for k, v in info.items()
                                           if k in ("ctx", "dtype")})
            else:
                info.update(kwargs)
                state = func(name=f"{self.prefix}h0_{i}", shape=shape, **info)
            states.append(state)
        return states

    def _flat_params(self):
        """Pack params into the reference's flat vector layout
        (rnn_layer.py _forward_kernel: weights then biases)."""
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(getattr(self, f"{j}{i}_i2h_weight").data().reshape((-1,)))
                ws.append(getattr(self, f"{j}{i}_h2h_weight").data().reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(getattr(self, f"{j}{i}_i2h_bias").data())
                bs.append(getattr(self, f"{j}{i}_h2h_bias").data())
        return nd.concat(*(ws + bs), dim=0)

    def forward(self, inputs, states=None):
        from ...symbol import Symbol
        if isinstance(inputs, Symbol):
            return super().forward(inputs, states)
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if isinstance(states, nd.NDArray):
            states = [states]
        for info, state in zip(self.state_info(batch_size), states):
            if state.shape != info["shape"]:
                raise ValueError(
                    f"Invalid recurrent state shape. Expecting "
                    f"{info['shape']}, got {state.shape}.")
        out = self._forward_kernel(inputs, states)
        return out[0] if skip_states else out

    def _finish_deferred(self, inputs):
        # complete deferred shapes from the input feature size
        ni = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        ng, nh = self._gates, self._hidden_size
        in_sz = ni
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                p = getattr(self, f"{j}{i}_i2h_weight")
                if p.shape is None or 0 in p.shape:
                    p.shape = (ng * nh, in_sz)
                if p._deferred_init:
                    p._finish_deferred_init()
                for nm in (f"{j}{i}_h2h_weight", f"{j}{i}_i2h_bias",
                           f"{j}{i}_h2h_bias"):
                    q = getattr(self, nm)
                    if q._deferred_init:
                        q._finish_deferred_init()
            in_sz = nh * self._dir

    def _forward_kernel(self, inputs, states):
        self._finish_deferred(inputs)
        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, 0, 1)
        params = self._flat_params()
        if self._mode == "lstm":
            rnn_args = [states[0], states[1]]
        else:
            rnn_args = [states[0]]
        rnn_out = nd.RNN(inputs, params, *rnn_args,
                         state_size=self._hidden_size,
                         num_layers=self._num_layers,
                         bidirectional=self._dir == 2, mode=self._mode,
                         p=self._dropout, state_outputs=True)
        outputs = rnn_out[0]
        states_out = list(rnn_out[1:])
        if self._layout == "NTC":
            outputs = nd.swapaxes(outputs, 0, 1)
        return outputs, states_out

    def hybrid_forward(self, F, inputs, states=None, **params):
        # symbolic path for export/shape inference
        if states is None:
            states = [F.zeros(())]
        sym_params = self._flat_params_sym(F)
        args = [states[0]] if self._mode != "lstm" else list(states[:2])
        out = F.RNN(inputs, sym_params, *args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=False)
        return out

    def _flat_params_sym(self, F):
        from ... import symbol as sym
        parts = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                parts.append(getattr(self, f"{j}{i}_i2h_weight").var().reshape((-1,)))
                parts.append(getattr(self, f"{j}{i}_h2h_weight").var().reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                parts.append(getattr(self, f"{j}{i}_i2h_bias").var())
                parts.append(getattr(self, f"{j}{i}_h2h_bias").var())
        return sym.Concat(*parts, dim=0)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (rnn_layer.py:307)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (rnn_layer.py:404)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm",
                         projection_size=projection_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (rnn_layer.py:535)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
