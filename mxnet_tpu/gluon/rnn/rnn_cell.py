"""Gluon recurrent cells.

Parity: python/mxnet/gluon/rnn/rnn_cell.py in the reference. Cells run one
timestep; unroll() composes timesteps. Under hybridize the unrolled graph
compiles into one XLA program (the fused multi-step path is rnn_layer.py's
lax.scan RNN op).
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ..parameter import Parameter
from ... import ndarray as nd

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, nd.NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            in_axis = in_layout.find("T") if in_layout else axis
            inputs = nd.split_v2(inputs, inputs.shape[in_axis], axis=in_axis,
                                 squeeze_axis=True)
            if not isinstance(inputs, (list, tuple)):
                inputs = [inputs]
    else:
        batch_size = inputs[0].shape[batch_axis - (1 if axis == 0 else 0)] \
            if axis == 0 else inputs[0].shape[batch_axis]
        if merge is True:
            inputs = nd.stack(*inputs, axis=axis)
    return inputs, axis, batch_size


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, nd.NDArray):
        data = nd.stack(*data, axis=time_axis)
    outputs = nd.SequenceMask(data, sequence_length=valid_length,
                              use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = nd.split_v2(outputs, outputs.shape[time_axis],
                              axis=time_axis, squeeze_axis=True)
    return outputs


class RecurrentCell(Block):
    """Abstract base class for RNN cells (gluon/rnn/rnn_cell.py:78)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states for this cell (rnn_cell.py:118)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            if func is None:
                state = nd.zeros(shape, **{k: v for k, v in info.items()
                                           if k in ("ctx", "dtype")})
            else:
                state = func(name=f"{self._prefix}begin_state_"
                                  f"{self._init_counter}", shape=shape, **info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unrolls the cell for `length` timesteps (rnn_cell.py:160)."""
        from ... import ndarray as F
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
        if merge_outputs is None:
            merge_outputs = False
        if merge_outputs and not isinstance(outputs, nd.NDArray):
            outputs = nd.stack(*outputs, axis=axis)
        elif merge_outputs is False and isinstance(outputs, nd.NDArray):
            outputs = nd.split_v2(outputs, outputs.shape[axis], axis=axis,
                                  squeeze_axis=True)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            if activation in ("tanh", "relu", "sigmoid", "softrelu",
                              "softsign"):
                return F.Activation(inputs, act_type=activation, **kwargs)
            return getattr(F, activation)(inputs, **kwargs)
        if isinstance(activation, Block):
            return activation(inputs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states) if False else \
            self.hybrid_call(inputs, states)

    def hybrid_call(self, inputs, states):
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell whose step is hybridizable (rnn_cell.py:340)."""

    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        params = {name: p.data() for name, p in self._reg_params.items()}
        from ... import ndarray as F
        return self.hybrid_forward(F, inputs, states, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(Wx + Rh + b) (rnn_cell.py:364)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size,
                               name=prefix + "h2h")
        i2h_plus_h2h = i2h + h2h
        output = self._get_activation(F, i2h_plus_h2h, self._activation,
                                      name=prefix + "out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (rnn_cell.py:463). Gate order (i, f, g, o) = cuDNN/ref."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4,
                                     name=prefix + "slice")
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (rnn_cell.py:599). Gate order (r, z, n) = cuDNN/ref."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "h2h")
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3,
                                           name=prefix + "i2h_slice")
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3,
                                           name=prefix + "h2h_slice")
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Sequentially stacking multiple cells (rnn_cell.py:705)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        _, _, batch_size = _format_sequence(length, inputs, layout, None)
        num_cells = len(self._children)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input (rnn_cell.py:790)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify a wrapped cell (rnn_cell.py:850)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified twice" \
            % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (rnn_cell.py:910)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        super().__init__(base_cell)
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_outputs, p_states = self._zoneout_outputs, self._zoneout_states

        def mask(p, like):
            m = F.Dropout(F.ones_like(like), p=p)
            return m

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0.0 else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds residual connection to a cell (rnn_cell.py:975)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        if isinstance(outputs, nd.NDArray):
            inputs, _, _ = _format_sequence(length, inputs, layout, True)
            outputs = outputs + inputs
        else:
            inputs, _, _ = _format_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Bidirectional RNN over two cells (rnn_cell.py:1030)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self._output_prefix = output_prefix
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        reversed_inputs = list(reversed(inputs))
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            reversed_r_outputs = list(reversed(r_outputs))
        else:
            stacked = nd.stack(*r_outputs, axis=0)
            rev = nd.SequenceReverse(stacked, sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
            reversed_r_outputs = nd.split_v2(rev, length, axis=0,
                                             squeeze_axis=True)
            if isinstance(reversed_r_outputs, nd.NDArray):
                reversed_r_outputs = [reversed_r_outputs]
        outputs = [nd.Concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
