"""Gluon utilities.

Parity: python/mxnet/gluon/utils.py (split_data, split_and_load,
clip_global_norm, check_sha1, download).
"""
from __future__ import annotations

import hashlib
import os

from .. import ndarray as nd

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Splits an NDArray into num_slice slices along batch_axis
    (gluon/utils.py:35)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's multiple of {num_slice} or set even_split=False.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(nd.slice_axis(data, axis=batch_axis, begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Splits an NDArray into len(ctx_list) slices and loads each onto one
    context (gluon/utils.py:81)."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescales arrays so that the sum of their 2-norm is <= max_norm
    (gluon/utils.py:115)."""
    assert len(arrays) > 0
    total_norm = nd.add_n(*[nd.sum(x * x).reshape((1,)) for x in arrays])
    total_norm = float(nd.sqrt(total_norm).asnumpy()[0])
    if check_isfinite:
        import math
        if not math.isfinite(total_norm):
            import warnings
            warnings.warn(
                UserWarning("nan or inf is detected. Clipping results will be "
                            "undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """Checks whether the sha1 hash of the file content matches
    (gluon/utils.py:173)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Download a file from a URL (gluon/utils.py:193). This environment has
    no egress; the function only serves cached files already on disk."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        f"download({url}): network egress is unavailable in this environment "
        f"and no cached copy exists at {fname}")
