"""Gluon Block / HybridBlock / SymbolBlock.

Parity: python/mxnet/gluon/block.py:229,839,1194 in the reference. TPU
redesign of hybridization: the reference's ``_build_cache`` traces
``hybrid_forward`` into a Symbol graph and wraps it in a C++ ``CachedOp``
(block.py:933,970); here ``hybridize()`` routes ``__call__`` through
``mxnet_tpu.jit.trace``, which re-runs the imperative code under ``jax.jit``
so the whole forward (and, when recording, the backward tape) compiles into
one XLA executable per input-shape signature — the same "compile once,
replay" contract with XLA doing memory planning and fusion.
"""
from __future__ import annotations

import copy
import re
import warnings
from collections import OrderedDict

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from .parameter import (Parameter, ParameterDict, DeferredInitializationError,
                        tensor_types)
from .. import initializer

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Scope for collecting child Blocks (gluon/block.py:34)."""

    _current = None
    _global_counter = {}  # top-level naming (reference: NameManager current)

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = _BlockScope._current
        if current is None:
            if prefix is None:
                prefix = _name_with_count(_BlockScope._global_counter,
                                          hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            prefix = _name_with_count(current._counter, hint) + "_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = _BlockScope._current
        _BlockScope._current = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current = self._old_scope


def _name_with_count(counter, hint):
    count = counter.get(hint, 0)
    counter[hint] = count + 1
    return f"{hint}{count}"


def _flatten(args, fmt_name):
    flat, fmts = [], []
    for a in args:
        if isinstance(a, tensor_types):
            flat.append(a)
            fmts.append(0)
        elif isinstance(a, (list, tuple)):
            f, fmt = _flatten(a, fmt_name)
            flat.extend(f)
            fmts.append(fmt)
        else:
            flat.append(a)
            fmts.append(-1)
    return flat, fmts


def _regroup(flat, fmt):
    if isinstance(fmt, int):
        if fmt == 0 or fmt == -1:
            return flat[0], flat[1:]
        return flat[:fmt], flat[fmt:]
    out = []
    for f in fmt:
        res, flat = _regroup(flat, f)
        out.append(res)
    return out, flat


class Block:
    """Base class for all neural network layers and models
    (gluon/block.py:229)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self.__dict__.items()
            if isinstance(block, Block))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Registers parameters and child blocks on assignment."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Returns a name-space scope object managing child block and
        parameter names."""
        return self._scope

    @property
    def params(self):
        """Returns this Block's parameter dictionary (not including children)."""
        return self._params

    def collect_params(self, select=None):
        """Returns a ParameterDict of this Block and all children
        (gluon/block.py:504)."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn):
        """Applies fn recursively to every child block and self."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    def initialize(self, init=initializer.Uniform(), ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def save_parameters(self, filename, deduplicate=False):
        """Save parameters to file (gluon/block.py:417). Format: the repo's
        NDArray dict container (see mxnet_tpu.ndarray.save)."""
        params = self._collect_params_with_prefix()
        nd.save(filename, {key: val._data if isinstance(val, Parameter) else val
                           for key, val in params.items()})

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val.data() for key, val in self._reg_params.items()
               if val._data is not None or val._deferred_init}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def _params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._params_with_prefix(prefix + name))
        return ret

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        """Load parameters from file (gluon/block.py:473)."""
        loaded = nd.load(filename)
        params = self._params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # legacy full-name format, fall back to ParameterDict.load
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name in loaded:
            if not ignore_extra and name not in params:
                raise ValueError(
                    f"Parameter '{name}' loaded from file '{filename}' is not "
                    "present in this block")
            if name in params:
                params[name].set_data(loaded[name])

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        """Override to implement forward computation using NDArray."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a summary of the network (gluon/block.py:601)."""
        summary = OrderedDict()
        hooks = []

        def _make_hook(name, blk):
            def hook(block, inputs, outputs):
                cname = name or block.__class__.__name__
                entry = summary.setdefault(cname, {"params": 0})
                entry["params"] = sum(
                    p.data().size for p in block.params.values()
                    if p._data is not None)
            return hook

        def _register(blk, name=""):
            hooks.append(blk.register_forward_hook(_make_hook(name, blk)))
            for cname, child in blk._children.items():
                _register(child, name + "." + cname if name else cname)

        _register(self)
        try:
            self(*inputs)
            print(f"{'Layer':<40}{'Params':<15}")
            print("=" * 55)
            total = 0
            for name, entry in summary.items():
                print(f"{name:<40}{entry['params']:<15}")
                total += entry["params"]
            print("=" * 55)
            print(f"Total params: {total}")
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks_dict):
        self.id = _HookHandle._next_id
        _HookHandle._next_id += 1
        self._hooks = hooks_dict

    def detach(self):
        self._hooks.pop(self.id, None)


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


class HybridBlock(Block):
    """A Block that can be compiled into one XLA executable
    (gluon/block.py:839).

    Subclasses implement ``hybrid_forward(F, x, *args, **params)`` where
    ``F`` is ``mxnet_tpu.nd`` (imperative) or ``mxnet_tpu.sym`` (symbolic
    export path) and registered parameters arrive as keyword arguments.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._traced = {}       # shape/dtype signature -> TracedFunction
        self._flags = {}
        self._v2 = type(self).hybrid_forward is HybridBlock.hybrid_forward

    def hybridize(self, active=True, **kwargs):
        """Activates XLA whole-graph compilation for this block and all
        children. The flags of the reference CachedOp (static_alloc,
        static_shape — cached_op.h:32) are accepted and ignored: XLA's
        buffer assignment is always static."""
        self._active = active
        self._flags.update(kwargs)
        self._traced = {}
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._traced = {}
        super().cast(dtype)

    def _all_params(self):
        ret = dict(self._reg_params)
        for child in self._children.values():
            ret.update(child._all_params() if isinstance(child, HybridBlock)
                       else child._reg_params)
        return ret

    def _deferred_infer_shape(self, *args):
        """Finish deferred parameter initialization by tracing the whole
        block symbolically and running shape inference — the analogue of
        _deferred_infer_shape (reference gluon/block.py:791)."""
        from .. import symbol as sym
        try:
            inputs = [sym.var(f"data{i}") for i in range(len(args))]
            out = self(*inputs)
            if isinstance(out, (list, tuple)):
                out = sym.Group(list(out))
            shapes = {f"data{i}": a.shape for i, a in enumerate(args)
                      if isinstance(a, tensor_types)}
            arg_shapes, _, aux_shapes = out.infer_shape_partial(**shapes)
            sdict = dict(zip(out.list_arguments(), arg_shapes))
            sdict.update(zip(out.list_auxiliary_states(), aux_shapes))
            for p in self._all_params().values():
                if p.name in sdict and sdict[p.name] is not None and \
                        p._deferred_init:
                    p.shape = sdict[p.name]
                    p._finish_deferred_init()
        except Exception as e:
            raise ValueError(
                "Deferred initialization failed because shape cannot be "
                "inferred: " + str(e)) from e

    def infer_shape(self, *args):
        self._deferred_infer_shape(*args)

    def _call_with_params(self, *args):
        params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **params)

    def forward(self, x, *args):
        """Defines the forward computation; wires params and jit. Symbol
        inputs route through hybrid_forward(sym, ...) — the export /
        shape-inference path."""
        from ..symbol import Symbol
        if isinstance(x, Symbol):
            params = {name: p.var() for name, p in self._reg_params.items()}
            return self.hybrid_forward(_sym_ns(), x, *args, **params)
        try:
            if self._active:
                return self._traced_call(x, *args)
            return self._call_with_params(x, *args)
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
        if self._active:
            return self._traced_call(x, *args)
        return self._call_with_params(x, *args)

    def _traced_call(self, *args):
        from .. import jit as _jit

        # inside an enclosing trace (a hybridized parent, or a user-level
        # mxnet_tpu.jit.trace step) run eagerly so everything fuses into the
        # one outer executable instead of nesting jits
        import jax.core as _jcore
        if _jit._sessions() or any(
                isinstance(a.data_, _jcore.Tracer)
                for a in args if isinstance(a, tensor_types)):
            return self._call_with_params(*args)
        key = tuple((a.shape, str(a.dtype)) if isinstance(a, tensor_types)
                    else a for a in args)
        fn = self._traced.get(key)
        if fn is None:
            # non-tensor extras (scalars, None, flags) become static args so
            # TracedFunction never asks them for .shape
            statics = tuple(i for i, a in enumerate(args)
                            if not isinstance(a, tensor_types))
            fn = _jit.trace(lambda *xs: self._call_with_params(*xs),
                            static_argnums=statics)
            self._traced[key] = fn
        return fn(*args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Override to implement forward computation over namespace F."""
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Export symbol graph + params for deployment
        (gluon/block.py:1081): ``path-symbol.json`` + ``path-%04d.params``."""
        from .. import symbol as sym
        out = self(sym.var("data"))
        if isinstance(out, (list, tuple)):
            out = sym.Group(list(out))
        out.save(f"{path}-symbol.json")
        arg_dict = {}
        for name, param in self.collect_params().items():
            if param._data is not None:
                arg_dict[name] = param.data()
        nd.save(f"{path}-{epoch:04d}.params", arg_dict)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"



def _sym_ns():
    from .. import symbol as sym
    return sym


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol (gluon/block.py:1194) — the import
    path for models exported with HybridBlock.export."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=None)
        # graph arguments keep their exported names — unprefixed dict
        # (reference block.py:1250 uses ParameterDict with empty prefix)
        self._params = ParameterDict("", None)
        from .. import symbol as sym
        if isinstance(inputs, sym.Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)):
            outputs = sym.Group(list(outputs))
        self._cached_graph = (inputs, outputs)
        input_names = {i.name for i in inputs}
        # every non-input argument becomes a Parameter
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            if name not in input_names:
                self.params.get(name, grad_req="null", allow_deferred_init=True)
        if params is not None:
            for name, value in params.items():
                if name in self.params:
                    self.params[name].shape = value.shape
                    self.params[name].set_data(value)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load a model exported by HybridBlock.export."""
        from .. import symbol as sym
        if isinstance(input_names, str):
            input_names = [input_names]
        outputs = sym.load(symbol_file)
        inputs = [sym.var(n) for n in input_names]
        ret = SymbolBlock(outputs, inputs)
        if param_file is not None:
            arrays = nd.load(param_file)
            for name, value in arrays.items():
                if name in ret.params:
                    ret.params[name].shape = value.shape
                    ret.params[name].set_data(value)
        if ctx is not None:
            ret.collect_params().reset_ctx(ctx)
        return ret

    def forward(self, x, *args):
        inputs, outputs = self._cached_graph
        feed = {}
        for i, a in zip(inputs, (x,) + args):
            feed[i.name] = a
        for name, p in self.params.items():
            feed[name] = p.data()
        res = outputs.eval(ctx=x.ctx, **feed)
        return res[0] if len(res) == 1 else res

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
