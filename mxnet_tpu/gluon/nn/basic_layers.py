"""Gluon basic neural network layers.

Parity: python/mxnet/gluon/nn/basic_layers.py in the reference (Dense :144,
BatchNorm :282, Embedding :379, LayerNorm :546, etc.). Every layer's
hybrid_forward dispatches through the op registry, so under hybridize the
whole network lowers to one XLA program with bf16-friendly matmuls on the MXU.
"""
from __future__ import annotations

import numpy as _np

from ..block import Block, HybridBlock
from ..parameter import Parameter
from ... import initializer

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU"]


class Sequential(Block):
    """Stacks Blocks sequentially (gluon/nn/basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks sequentially (gluon/nn/basic_layers.py:98)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def _call_with_params(self, *args):
        x = args[0]
        for block in self._children.values():
            x = block(x)
        return x

    def forward(self, x, *args):
        from ...symbol import Symbol
        if isinstance(x, Symbol):
            for block in self._children.values():
                x = block(x)
            return x
        return super().forward(x, *args)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: ``out = act(dot(x, w.T) + b)``
    (gluon/nn/basic_layers.py:144). Lowered to one MXU dot_general."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=_np.float32, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "{name}({layout}, {act})".format(
            name=self.__class__.__name__,
            act=self.act if self.act else "linear",
            layout=f"{shape[1] if len(shape) > 1 and shape[1] else None} -> {shape[0]}")


class Dropout(HybridBlock):
    """Dropout regularization (gluon/nn/basic_layers.py:235)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"{self.__class__.__name__}(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization (gluon/nn/basic_layers.py:282). Running stats are
    auxiliary params mutated by the op (mutate slots), matching the
    reference's aux-state semantics."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def cast(self, dtype):
        if _np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           name="fwd", **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join(f"{k}={v}" for k, v in self._kwargs.items()))


class Embedding(HybridBlock):
    """Turns non-negative integers into dense vectors
    (gluon/nn/basic_layers.py:379). Lowered to XLA gather; gradient is a
    dense scatter-add (the TPU replacement for row_sparse grads —
    SURVEY.md §7 hard part 4)."""

    def __init__(self, input_dim, output_dim, dtype=_np.float32,
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        if sparse_grad:
            import warnings
            warnings.warn("sparse_grad is not supported on TPU; using dense "
                          "gradients", stacklevel=2)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": _np.dtype(dtype).name}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """Flattens the input to (batch, -1) (gluon/nn/basic_layers.py:435)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    """Instance normalization (gluon/nn/basic_layers.py:457)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", eps=self._epsilon)
        x = x.swapaxes(1, self._axis) if hasattr(x, "swapaxes") else x
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join(f"{k}={v}" for k, v in self._kwargs.items()))


class LayerNorm(HybridBlock):
    """Layer normalization (gluon/nn/basic_layers.py:546)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.LayerNorm(data, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join(f"{k}={v}" for k, v in self._kwargs.items()))


class GroupNorm(HybridBlock):
    """Group normalization (gluon/nn/basic_layers.py:625)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "num_groups": num_groups,
                        "center": center, "scale": scale}
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(num_groups,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(num_groups,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.GroupNorm(data, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)

    def __repr__(self):
        return "{name}({content})".format(
            name=self.__class__.__name__,
            content=", ".join(f"{k}={v}" for k, v in self._kwargs.items()))


class Lambda(Block):
    """Wraps an operator or expression as a Block
    (gluon/nn/basic_layers.py:701)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    """Wraps an operator or expression as a HybridBlock
    (gluon/nn/basic_layers.py:746)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            from ... import symbol as sym
            assert hasattr(nd, function) and hasattr(sym, function), \
                f"Function name {function} is not found in ndarray/symbol."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


# ------------------------------------------------------------- activations
# Parity: python/mxnet/gluon/nn/activations.py

class Activation(HybridBlock):
    """Applies an activation function (relu/sigmoid/tanh/softrelu/softsign)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type, name="fwd")

    def __repr__(self):
        return f"{self.__class__.__name__}({self._act_type})"


class LeakyReLU(HybridBlock):
    """Leaky ReLU (gluon/nn/activations.py:77)."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be no less than 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha, name="fwd")

    def __repr__(self):
        return f"{self.__class__.__name__}({self._alpha})"


class PReLU(HybridBlock):
    """Parametric leaky ReLU (gluon/nn/activations.py:114)."""

    def __init__(self, alpha_initializer=initializer.Constant(0.25),
                 in_channels=1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu", name="fwd")


class ELU(HybridBlock):
    """Exponential Linear Unit (gluon/nn/activations.py:153)."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled Exponential Linear Unit (gluon/nn/activations.py:184)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu", name="fwd")


class Swish(HybridBlock):
    """Swish: x * sigmoid(beta*x) (gluon/nn/activations.py:210)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Gaussian Error Linear Unit (gluon/nn/activations.py:234)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu", name="fwd")
