"""Gluon convolution and pooling layers.

Parity: python/mxnet/gluon/nn/conv_layers.py:177-1200 in the reference.
All convs lower to one XLA conv_general_dilated (MXU); pooling to
lax.reduce_window. Layout is NCHW/OIHW like the reference's public API —
XLA re-lays-out internally for the TPU.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuple(v, n):
    if isinstance(v, (int, _np.integer)):
        return (int(v),) * n
    # asymmetric (lo, hi) padding pairs pass through untouched
    return tuple(tuple(int(y) for y in x) if isinstance(x, (tuple, list))
                 else int(x) for x in v)


class _Conv(HybridBlock):
    """Base for conv layers (reference gluon/nn/conv_layers.py:33)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        strides = _tuple(strides, ndim)
        padding = _tuple(padding, ndim)
        dilation = _tuple(dilation, ndim)
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = _tuple(adj, ndim)

        with self.name_scope():
            if op_name == "Convolution":
                if layout and layout[1] != "C":  # channels-last: OHWI weights
                    wshape = (channels,) + tuple(kernel_size) + \
                        (in_channels // groups,)
                else:
                    wshape = (channels, in_channels // groups) + \
                        tuple(kernel_size)
            else:  # Deconvolution: weight is (in, out/groups, *k)
                wshape = (in_channels, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, name="fwd", **self._kwargs)
        else:
            act = op(x, weight, bias, name="fwd", **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def _alias(self):
        return "conv"

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if hasattr(self, "out_pad") and self.out_pad != (0,) * len_kernel_size:
            s += ", output_padding={out_pad}".format(out_pad=self.out_pad)
        if self._kwargs["num_group"] != 1:
            s += ", groups={num_group}"
        if self.bias is None:
            s += ", bias=False"
        if self.act:
            s += ", {}".format(self.act)
        s += ")"
        shape = self.weight.shape
        layout = self._kwargs.get("layout")
        in_ch = shape[-1] if (layout and layout[1] != "C") else shape[1]
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(in_ch or None, shape[0]),
                        **self._kwargs)


class Conv1D(_Conv):
    """1D convolution (gluon/nn/conv_layers.py:177)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """2D convolution (gluon/nn/conv_layers.py:257)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        assert layout in ("NCHW", "NHWC"), "layout must be NCHW or NHWC"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """3D convolution (gluon/nn/conv_layers.py:341)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """Transposed 1D convolution (gluon/nn/conv_layers.py:426)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        if isinstance(output_padding, int):
            output_padding = (output_padding,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)
        self.out_pad = output_padding


class Conv2DTranspose(_Conv):
    """Transposed 2D convolution (gluon/nn/conv_layers.py:514)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        assert layout == "NCHW", \
            "Deconvolution supports only NCHW (no NHWC kernel path)"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)
        self.out_pad = output_padding


class Conv3DTranspose(_Conv):
    """Transposed 3D convolution (gluon/nn/conv_layers.py:606)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)
        self.out_pad = output_padding


class _Pooling(HybridBlock):
    """Base for pooling layers (gluon/nn/conv_layers.py:699)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        strides = _tuple(strides, len(pool_size))
        padding = _tuple(padding, len(pool_size))
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, name="fwd", **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}, ceil_mode={ceil_mode})".format(
            name=self.__class__.__name__,
            ceil_mode=self._kwargs["pooling_convention"] == "full",
            **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        if isinstance(pool_size, int):
            pool_size = (pool_size,)
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        assert layout in ("NCHW", "NHWC"), "layout must be NCHW or NHWC"
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        if isinstance(pool_size, int):
            pool_size = (pool_size,)
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        assert layout in ("NCHW", "NHWC"), "layout must be NCHW or NHWC"
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        super().__init__((1,), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        assert layout in ("NCHW", "NHWC"), "layout must be NCHW or NHWC"
        super().__init__((1, 1), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        super().__init__((1, 1, 1), None, 0, True, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        assert layout == "NCW", "Only NCW layout is supported"
        super().__init__((1,), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        assert layout in ("NCHW", "NHWC"), "layout must be NCHW or NHWC"
        super().__init__((1, 1), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        super().__init__((1, 1, 1), None, 0, True, True, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Pads the input tensor using the reflection of the boundary
    (gluon/nn/conv_layers.py:1156)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        assert len(padding) == 8
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
