"""Gluon Parameter / ParameterDict / Constant.

Parity: python/mxnet/gluon/parameter.py:47,706 in the reference — deferred
initialization, grad_req handling, per-context data, save/load. TPU redesign:
a Parameter owns ONE NDArray (a jax.Array committed to a Context); replication
across devices is not done by keeping N copies (the reference's per-GPU
`_data` list) but by sharding annotations applied when the training step is
pjit-ed over a Mesh (see mxnet_tpu/parallel). `list_data()` therefore returns
a single-element list in the single-logical-device model.
"""
from __future__ import annotations

import re
import warnings
from collections import OrderedDict

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from .. import initializer

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (nd.NDArray,)


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization
    (gluon/parameter.py:36)."""


class Parameter:
    """A Container holding parameters (weights) of Blocks.

    Parity: python/mxnet/gluon/parameter.py:47. ``shape`` entries of 0 are
    unknown and resolved at first forward (deferred init).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx = None
        self._deferred_init = ()
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        if stype not in ("default",) or grad_stype not in ("default",):
            # sparse storage is out of scope on TPU (SURVEY.md §7 hard part 4)
            warnings.warn("sparse parameter storage is not supported on TPU; "
                          "using dense", stacklevel=2)
        self.grad_req = grad_req

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # ------------------------------------------------------------------ props
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), \
            f"grad_req must be one of write, add, null, but got {req}"
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data.grad_req = "null"
            elif self._grad is None:
                self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        assert len(self._shape) == len(new_shape) and \
            all(j in (0, i) for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape {self._shape}."
        self._shape = tuple(new_shape)

    # ----------------------------------------------------------------- init
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of data "
                "through the network before accessing Parameters.")
        raise RuntimeError(
            f"Parameter '{self.name}' has not been initialized. Note that you "
            "should initialize parameters and create Trainer with "
            "Block.collect_params() instead of Block.params")

    def initialize(self, init=None, ctx=None, default_init=initializer.Uniform(),
                   force_reinit=False):
        """Initialize parameter and gradient arrays
        (gluon/parameter.py:361)."""
        if self._data is not None and not force_reinit:
            warnings.warn(f"Parameter '{self.name}' is already initialized, "
                          "ignoring. Set force_reinit=True to re-initialize.",
                          stacklevel=2)
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if self._shape is None or any(s == 0 for s in self._shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(f"Cannot initialize Parameter '{self.name}' "
                             "because it has invalid shape: "
                             f"{self._shape}.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        assert self._shape is not None and all(s > 0 for s in self._shape), \
            f"Cannot initialize Parameter '{self.name}' because it has " \
            f"invalid shape: {self._shape}."
        from .. import autograd
        from ..jit import no_trace
        with autograd.pause(), no_trace():
            if data is None:
                data = nd.zeros(self._shape, dtype=self.dtype, ctx=cpu())
                if isinstance(init, str):
                    init = initializer.create(init)
                init(initializer.InitDesc(self.name), data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx = list(ctx_list)
        self._data = data.copyto(self._ctx[0]) if data.ctx != self._ctx[0] else data
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._data.attach_grad(grad_req=self._grad_req)
        self._grad = self._data.grad

    # ----------------------------------------------------------------- data
    def data(self, ctx=None):
        """Returns the parameter on one context (gluon/parameter.py:549)."""
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(f"Parameter '{self.name}' has not been initialized")
        return list(self._ctx)

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        self._check_initialized()
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        if self._grad is None:
            return
        g = self._data.grad
        g._set_data(nd.zeros(g.shape, dtype=g.dtype, ctx=g.ctx).data_)

    def set_data(self, data):
        """Sets this parameter's value on all contexts
        (gluon/parameter.py:589)."""
        self.shape = data.shape
        if not isinstance(data, nd.NDArray):
            data = nd.array(data, dtype=self.dtype)
        if self._data is None:
            # loading weights IS initialization (reference _load_init,
            # gluon/parameter.py:274) — works on never-initialized params too
            if self._deferred_init:
                init, ctx, default_init, _ = self._deferred_init
            else:
                init, ctx, default_init = self.init, [current_context()], \
                    initializer.Uniform()
            self._deferred_init = (init, ctx, default_init, data)
            self._finish_deferred_init()
            return
        if not isinstance(data, nd.NDArray):
            data = nd.array(data, dtype=self.dtype)
        self._data._set_data(data.copyto(self._ctx[0]).astype(self.dtype).data_)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = self._data.copyto(ctx[0])
            grad_req = self._grad_req
            self._grad = None
            self._init_impl(data, ctx)
            self.grad_req = grad_req
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(f"Cannot reset context for Parameter '{self.name}' "
                             "because it has not been initialized.")

    def cast(self, dtype):
        """Cast data and gradient of this Parameter to a new data type."""
        self.dtype = dtype
        if self._data is None:
            return
        from .. import autograd
        with autograd.pause():
            data = self._data.astype(dtype)
            grad_req = self._grad_req
            self._grad = None
            self._init_impl(data, self._ctx)
            self.grad_req = grad_req

    # --------------------------------------------------------------- symbol
    def var(self):
        """Returns a symbol representing this parameter."""
        if self._var is None:
            from .. import symbol
            self._var = symbol.var(self.name, shape=self.shape, dtype=self.dtype,
                                   lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                                   init=self.init)
        return self._var


class Constant(Parameter):
    """A constant parameter for holding non-differentiable values
    (gluon/parameter.py:652)."""

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(value)
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self, _, arr):
                value.copyto(arr)

        init_name = f"Constant_{name}"
        initializer.register(Init)
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=Init(), differentiable=False)


class ParameterDict:
    """A dictionary managing a set of Parameters (gluon/parameter.py:706)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self.values())
        return f"{type(self).__name__}({self._prefix}\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Retrieves or creates a Parameter named prefix+name
        (gluon/parameter.py:817)."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
            return param
        for k, v in kwargs.items():
            if hasattr(param, k) and getattr(param, k) is not None:
                existing = getattr(param, k)
                if k == "shape" and v is not None and len(v) == len(existing):
                    inferred = tuple(
                        max(i, j) if 0 in (i, j) else i
                        for i, j in zip(v, existing))
                    if all(i in (0, j) or j in (0, i)
                           for i, j in zip(v, existing)):
                        param._shape = inferred
                        continue
                if k == "dtype" and _np.dtype(v) == _np.dtype(existing):
                    continue
                assert v is None or str(v) == str(existing), \
                    f"Cannot retrieve Parameter '{name}' because desired " \
                    f"attribute does not match with stored for attribute " \
                    f"'{k}': desired '{v}' vs stored '{getattr(param, k)}'."
            else:
                setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'.")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    f"Cannot update self with other because they have " \
                    f"different Parameters with the same name '{k}'"
            else:
                self._params[k] = v

    def initialize(self, init=initializer.Uniform(), ctx=None, verbose=False,
                   force_reinit=False):
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    f"Prefix '{strip_prefix}' is to be stripped before saving, "
                    f"but Parameter's name '{param.name}' does not start with it.")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name}' is missing in file '{filename}'."
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    f"Parameter '{name}' loaded from file '{filename}' is " \
                    "not present in ParameterDict"
                continue
            self[name].set_data(arg_dict[name].copyto(ctx) if ctx else arg_dict[name])
