"""Decoder-only transformer LM for the model zoo (docs/parallel.md).

The pretraining workload the dp×fsdp×tp stack is measured on: built
entirely from existing nn blocks (Embedding / MultiHeadAttention /
Dense / LayerNorm / contrib.Remat) with the stable parameter prefixes
``parallel.SpecLayout.param_rules`` is written against —
``attn_qkv_``/``attn_out_`` (tp column/row parallel), ``ff1_``/``ff2_``
(MLP up/down), ``embed_``/``head_`` (vocab tables over fsdp×tp).

Pre-norm residual blocks (ln -> attn -> +x; ln -> ff -> +x), GELU MLP,
learned positional embeddings, causal attention. ``impl`` selects the
attention kernel exactly as MultiHeadAttention does: 'dense' (XLA),
'flash' (Pallas, schedules from the PR-15 table), 'ring'
(sequence-parallel over ``sp_axis``), or 'auto'. ``remat`` wraps every
block in contrib.Remat with a resolve_policy spec (remat.py).
``final_norm=False`` builds the deliberately overflow-prone config the
numerics drills train to divergence.
"""
from __future__ import annotations

from .. import nn
from ..block import HybridBlock
from ..contrib import nn as contrib_nn

__all__ = ["TransformerBlock", "TransformerLM", "transformer_lm"]


class TransformerBlock(HybridBlock):
    """One pre-norm decoder block: causal self-attention + GELU MLP."""

    def __init__(self, units, num_heads, impl="dense", mesh=None,
                 sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.attn = contrib_nn.MultiHeadAttention(
                units, num_heads, impl=impl, causal=True, mesh=mesh,
                sp_axis=sp_axis, prefix="attn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ff1 = nn.Dense(units * 4, activation="gelu",
                                flatten=False, in_units=units,
                                prefix="ff1_")
            self.ff2 = nn.Dense(units, flatten=False, in_units=units * 4,
                                prefix="ff2_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        return x + self.ff2(self.ff1(self.ln2(x)))


class TransformerLM(HybridBlock):
    """Decoder-only LM: token+position embed -> blocks -> [norm] -> head.

    Input is (B, T) token ids; output is (B, T, vocab) logits.
    """

    def __init__(self, vocab, units, num_heads, num_layers, max_len=512,
                 impl="dense", mesh=None, sp_axis="sp", remat=None,
                 final_norm=True, **kwargs):
        super().__init__(**kwargs)
        self._max_len = max_len
        with self.name_scope():
            self.embed = nn.Embedding(vocab, units, prefix="embed_")
            self.pos = nn.Embedding(max_len, units, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    blk = TransformerBlock(units, num_heads, impl=impl,
                                           mesh=mesh, sp_axis=sp_axis)
                    if remat is not None:
                        blk = contrib_nn.Remat(blk, policy=remat)
                    self.blocks.add(blk)
            self.norm = nn.LayerNorm(prefix="norm_") if final_norm else None
            self.head = nn.Dense(vocab, flatten=False, in_units=units,
                                 prefix="head_")

    def hybrid_forward(self, F, x):
        t = x.shape[1]
        if t > self._max_len:
            raise ValueError(f"sequence length {t} exceeds max_len "
                             f"{self._max_len}")
        # int32 positions on purpose: a float arange would ride the AMP
        # bf16 cast, where integers above 256 stop being exact
        h = self.embed(x) + self.pos(F.arange(0, t, dtype="int32"))
        h = self.blocks(h)
        if self.norm is not None:
            h = self.norm(h)
        return self.head(h)


def transformer_lm(vocab=64, units=64, num_heads=2, num_layers=2,
                   max_len=512, impl="dense", mesh=None, sp_axis="sp",
                   remat=None, final_norm=True, **kwargs):
    """Factory with CI-sized defaults (shapes divide a dp=2×fsdp=2×tp=2
    mesh: vocab % (fsdp·tp) == 0, 3·units % tp == 0, units % fsdp == 0)."""
    return TransformerLM(vocab, units, num_heads, num_layers,
                         max_len=max_len, impl=impl, mesh=mesh,
                         sp_axis=sp_axis, remat=remat,
                         final_norm=final_norm, **kwargs)
