"""Decoder-only transformer LM for the model zoo (docs/parallel.md).

The pretraining workload the dp×fsdp×tp stack is measured on: built
entirely from existing nn blocks (Embedding / MultiHeadAttention /
Dense / LayerNorm / contrib.Remat) with the stable parameter prefixes
``parallel.SpecLayout.param_rules`` is written against —
``attn_qkv_``/``attn_out_`` (tp column/row parallel), ``ff1_``/``ff2_``
(MLP up/down), ``embed_``/``head_`` (vocab tables over fsdp×tp).

Pre-norm residual blocks (ln -> attn -> +x; ln -> ff -> +x), GELU MLP,
learned positional embeddings, causal attention. ``impl`` selects the
attention kernel exactly as MultiHeadAttention does: 'dense' (XLA),
'flash' (Pallas, schedules from the PR-15 table), 'ring'
(sequence-parallel over ``sp_axis``), or 'auto'. ``remat`` wraps every
block in contrib.Remat with a resolve_policy spec (remat.py).
``final_norm=False`` builds the deliberately overflow-prone config the
numerics drills train to divergence.
"""
from __future__ import annotations

from .. import nn
from ..block import HybridBlock
from ..contrib import nn as contrib_nn

__all__ = ["TransformerBlock", "TransformerLM", "transformer_lm",
           "decode_spec", "decode_param_names", "paged_prefill",
           "paged_step", "flat_forward"]


class TransformerBlock(HybridBlock):
    """One pre-norm decoder block: causal self-attention + GELU MLP."""

    def __init__(self, units, num_heads, impl="dense", mesh=None,
                 sp_axis="sp", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.attn = contrib_nn.MultiHeadAttention(
                units, num_heads, impl=impl, causal=True, mesh=mesh,
                sp_axis=sp_axis, prefix="attn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ff1 = nn.Dense(units * 4, activation="gelu",
                                flatten=False, in_units=units,
                                prefix="ff1_")
            self.ff2 = nn.Dense(units, flatten=False, in_units=units * 4,
                                prefix="ff2_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        return x + self.ff2(self.ff1(self.ln2(x)))


class TransformerLM(HybridBlock):
    """Decoder-only LM: token+position embed -> blocks -> [norm] -> head.

    Input is (B, T) token ids; output is (B, T, vocab) logits.
    """

    def __init__(self, vocab, units, num_heads, num_layers, max_len=512,
                 impl="dense", mesh=None, sp_axis="sp", remat=None,
                 final_norm=True, **kwargs):
        super().__init__(**kwargs)
        self._max_len = max_len
        with self.name_scope():
            self.embed = nn.Embedding(vocab, units, prefix="embed_")
            self.pos = nn.Embedding(max_len, units, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    blk = TransformerBlock(units, num_heads, impl=impl,
                                           mesh=mesh, sp_axis=sp_axis)
                    if remat is not None:
                        blk = contrib_nn.Remat(blk, policy=remat)
                    self.blocks.add(blk)
            self.norm = nn.LayerNorm(prefix="norm_") if final_norm else None
            self.head = nn.Dense(vocab, flatten=False, in_units=units,
                                 prefix="head_")

    def hybrid_forward(self, F, x):
        t = x.shape[1]
        if t > self._max_len:
            raise ValueError(f"sequence length {t} exceeds max_len "
                             f"{self._max_len}")
        # int32 positions on purpose: a float arange would ride the AMP
        # bf16 cast, where integers above 256 stop being exact
        h = self.embed(x) + self.pos(F.arange(0, t, dtype="int32"))
        h = self.blocks(h)
        if self.norm is not None:
            h = self.norm(h)
        return self.head(h)


def transformer_lm(vocab=64, units=64, num_heads=2, num_layers=2,
                   max_len=512, impl="dense", mesh=None, sp_axis="sp",
                   remat=None, final_norm=True, **kwargs):
    """Factory with CI-sized defaults (shapes divide a dp=2×fsdp=2×tp=2
    mesh: vocab % (fsdp·tp) == 0, 3·units % tp == 0, units % fsdp == 0)."""
    return TransformerLM(vocab, units, num_heads, num_layers,
                         max_len=max_len, impl=impl, mesh=mesh,
                         sp_axis=sp_axis, remat=remat,
                         final_norm=final_norm, **kwargs)


# --------------------------------------------------- paged decode forward
#
# The step-wise forward of the generative serving runtime
# (serving/decode.py): pure functions over a flat parameter tuple that
# read and write the paged KV cache, mirroring hybrid_forward's math
# op-for-op (LayerNorm eps=1e-5, jax.nn.gelu, 1/sqrt(head_dim) scaled
# causal attention) so greedy decode matches the full-context forward
# argmax token-for-token. Parameter VALUES stay runtime operands — the
# functions compile once per shape under capture and a weight swap
# never retraces.

# canonical per-block parameter suffix order (matches name_scope output)
_BLOCK_PARAM_SUFFIXES = (
    "ln1_gamma", "ln1_beta", "attn_qkv_weight", "attn_qkv_bias",
    "attn_out_weight", "attn_out_bias", "ln2_gamma", "ln2_beta",
    "ff1_weight", "ff1_bias", "ff2_weight", "ff2_bias")


def decode_spec(net):
    """Static decode identity of an initialized :class:`TransformerLM`:
    the shape facts the compiled prefill/step programs specialize on
    (``remat``-wrapped blocks are a training construct and rejected —
    decode reads the plain block stack)."""
    blocks = list(net.blocks)
    for blk in blocks:
        if not isinstance(blk, TransformerBlock):
            raise ValueError(
                "decode_spec: TransformerLM blocks must be plain "
                f"TransformerBlock (got {type(blk).__name__}; build the "
                "serving model with remat=None)")
    vocab, units = net.embed.weight.shape
    return {
        "vocab": int(vocab), "units": int(units),
        "num_heads": int(blocks[0].attn._heads),
        "num_layers": len(blocks), "max_len": int(net._max_len),
        "final_norm": net.norm is not None,
    }


def decode_param_names(spec, names):
    """Order a collected parameter-name iterable (``collect_params()``
    keys, or a Predictor's bound arg names) into the canonical flat
    tuple layout ``paged_prefill``/``paged_step`` consume: embed, pos,
    per-block suffixes, [final norm,] head. Matching is by unambiguous
    name suffix, so the gensym block prefix never matters."""
    names = list(names)

    def find(suffix):
        hits = [n for n in names if n.endswith(suffix)]
        if len(hits) != 1:
            raise ValueError(
                f"decode_param_names: expected exactly one param ending "
                f"'{suffix}', found {hits or 'none'}")
        return hits[0]

    ordered = [find("embed_weight"), find("pos_weight")]
    for i in range(spec["num_layers"]):
        blk = f"block{i}_"
        for suffix in _BLOCK_PARAM_SUFFIXES:
            ordered.append(find(blk + suffix))
    if spec["final_norm"]:
        ordered += [find("norm_gamma"), find("norm_beta")]
    ordered += [find("head_weight"), find("head_bias")]
    return ordered


def _ln(x, gamma, beta):
    import jax
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + 1e-5) * gamma + beta


def _dense(x, w, b):
    import jax

    return jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (1,)), ((), ()))) + b


def _split_qkv(qkv, num_heads):
    """(..., 3U) fused projection -> q, k, v of (..., H, D) — the same
    channel layout contrib.nn.MultiHeadAttention's reshape/slice
    produces, so paged KV state is interchangeable with the dense
    path's."""
    u = qkv.shape[-1] // 3
    d = u // num_heads
    q, k, v = qkv[..., :u], qkv[..., u:2 * u], qkv[..., 2 * u:]
    shape = qkv.shape[:-1] + (num_heads, d)
    return q.reshape(shape), k.reshape(shape), v.reshape(shape)


def _page_scatter(pages, scales, vals, page_idx, slot_idx, quantize):
    """Write per-token K or V rows into the page pool; with an int8
    pool, quantize on write and update the per-slot scales."""
    if quantize:
        from ...ops.decode_attention import kv_quantize

        qv, sc = kv_quantize(vals)
        return (pages.at[page_idx, slot_idx].set(qv),
                scales.at[page_idx, slot_idx].set(sc))
    return pages.at[page_idx, slot_idx].set(vals.astype(pages.dtype)), \
        scales


def _block_params(params, i):
    base = 2 + i * len(_BLOCK_PARAM_SUFFIXES)
    return params[base:base + len(_BLOCK_PARAM_SUFFIXES)]


def _head_logits(params, spec, h):
    if spec["final_norm"]:
        h = _ln(h, params[-4], params[-3])
    return _dense(h, params[-2], params[-1])


def flat_forward(params, spec, tokens):
    """Full-context forward over the flat parameter tuple: (B, T) int32
    -> (B, T, vocab) logits, the same math ``hybrid_forward`` runs —
    the decode predictor's fixed-shape probe/eval surface, compiled
    from the SAME swappable cells the paged path reads."""
    import jax
    import jax.numpy as jnp

    b, t = tokens.shape
    heads = spec["num_heads"]
    d = spec["units"] // heads
    pos = jnp.minimum(jnp.arange(t, dtype=jnp.int32),
                      spec["max_len"] - 1)
    h = params[0][tokens] + params[1][pos]
    causal = pos[:, None] >= pos[None, :]
    for i in range(spec["num_layers"]):
        (ln1_g, ln1_b, qkv_w, qkv_b, out_w, out_b, ln2_g, ln2_b,
         ff1_w, ff1_b, ff2_w, ff2_b) = _block_params(params, i)
        q, k, v = _split_qkv(_dense(_ln(h, ln1_g, ln1_b), qkv_w, qkv_b),
                             heads)                   # (B, T, H, D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, h.dtype))
        s = jnp.where(causal[None, None], s, -1e30)
        attn = jnp.einsum("bhqk,bkhd->bqhd",
                          jax.nn.softmax(s, axis=-1), v)
        h = h + _dense(attn.reshape(b, t, -1), out_w, out_b)
        ff = jax.nn.gelu(_dense(_ln(h, ln2_g, ln2_b), ff1_w, ff1_b))
        h = h + _dense(ff, ff2_w, ff2_b)
    return _head_logits(params, spec, h)


def paged_prefill(params, spec, tokens, true_len, kv, page_row,
                  interpret=False):
    """Run one prompt through the full stack, writing per-layer K/V into
    the pages ``page_row`` maps, and return the last true token's
    logits.

    ``tokens`` (1, T) int32 padded to its bucket; ``true_len`` (1,)
    int32; ``kv`` the flat cache tuple (k_pages, v_pages, k_scales,
    v_scales) with layer axis 0 on each; ``page_row`` (max_pages,)
    int32 with unused slots pointing at scratch page 0. Attention
    inside the window is the ordinary causal dense form — the paged
    kernel is for the one-token steady state.
    """
    import jax
    import jax.numpy as jnp

    k_pages, v_pages, k_scales, v_scales = kv
    quantize = k_pages.dtype == jnp.int8
    page_size = k_pages.shape[2]
    t = tokens.shape[1]
    heads = spec["num_heads"]
    d = spec["units"] // heads
    pos = jnp.arange(t, dtype=jnp.int32)
    live = pos < true_len[0]
    # padded tail positions clamp into range; their writes land on the
    # scratch page and their keys are causally invisible to true rows
    pos_ids = jnp.minimum(pos, spec["max_len"] - 1)
    h = params[0][tokens[0]] + params[1][pos_ids]     # (T, U)
    page_idx = jnp.where(live, page_row[pos // page_size], 0)
    slot_idx = pos % page_size
    causal = pos[:, None] >= pos[None, :]             # (T, T) q >= k
    new_k, new_v = [], []
    for i in range(spec["num_layers"]):
        (ln1_g, ln1_b, qkv_w, qkv_b, out_w, out_b, ln2_g, ln2_b,
         ff1_w, ff1_b, ff2_w, ff2_b) = _block_params(params, i)
        q, k, v = _split_qkv(_dense(_ln(h, ln1_g, ln1_b), qkv_w, qkv_b),
                             heads)                   # (T, H, D)
        kp, ks = _page_scatter(k_pages[i], k_scales[i], k, page_idx,
                               slot_idx, quantize)
        vp, vs = _page_scatter(v_pages[i], v_scales[i], v, page_idx,
                               slot_idx, quantize)
        new_k.append((kp, ks))
        new_v.append((vp, vs))
        s = jnp.einsum("qhd,khd->hqk", q, k) / jnp.sqrt(
            jnp.asarray(d, h.dtype))
        s = jnp.where(causal[None], s, -1e30)
        attn = jnp.einsum("hqk,khd->qhd", jax.nn.softmax(s, axis=-1), v)
        h = h + _dense(attn.reshape(t, -1), out_w, out_b)
        ff = jax.nn.gelu(_dense(_ln(h, ln2_g, ln2_b), ff1_w, ff1_b))
        h = h + _dense(ff, ff2_w, ff2_b)
    logits = _head_logits(params, spec,
                          jnp.take(h, true_len[0] - 1, axis=0))
    kv_out = (jnp.stack([k for k, _ in new_k]),
              jnp.stack([v for v, _ in new_v]),
              jnp.stack([s for _, s in new_k]),
              jnp.stack([s for _, s in new_v]))
    return logits, kv_out


def paged_step(params, spec, tokens, positions, active, kv, page_table,
               interpret=False):
    """ONE fixed-shape decode step for every live sequence slot: embed
    the last sampled token per row, append its K/V to the paged cache,
    attend over each row's pages through the tuned paged kernel, and
    return the next greedy token per row.

    ``tokens``/``positions``/``active`` (B,) int32; ``kv`` the flat
    cache tuple; ``page_table`` (B, max_pages) int32. Row membership,
    lengths and the table are all runtime operands — admitting or
    evicting sequences never changes the compiled program.
    """
    import jax
    import jax.numpy as jnp

    from ...ops.decode_attention import paged_decode_attention

    k_pages, v_pages, k_scales, v_scales = kv
    quantize = k_pages.dtype == jnp.int8
    page_size = k_pages.shape[2]
    heads = spec["num_heads"]
    b = tokens.shape[0]
    pos_ids = jnp.minimum(positions, spec["max_len"] - 1)
    h = params[0][tokens] + params[1][pos_ids]        # (B, U)
    # inactive rows write the scratch page; their gathers are masked by
    # length so the garbage never reaches a live row
    page_idx = jnp.where(
        active > 0,
        jnp.take_along_axis(page_table,
                            (pos_ids // page_size)[:, None],
                            axis=1)[:, 0],
        0)
    slot_idx = pos_ids % page_size
    lengths = jnp.where(active > 0, positions + 1, 1)
    new_k, new_v = [], []
    for i in range(spec["num_layers"]):
        (ln1_g, ln1_b, qkv_w, qkv_b, out_w, out_b, ln2_g, ln2_b,
         ff1_w, ff1_b, ff2_w, ff2_b) = _block_params(params, i)
        q, k, v = _split_qkv(_dense(_ln(h, ln1_g, ln1_b), qkv_w, qkv_b),
                             heads)                   # (B, H, D)
        kp, ks = _page_scatter(k_pages[i], k_scales[i], k, page_idx,
                               slot_idx, quantize)
        vp, vs = _page_scatter(v_pages[i], v_scales[i], v, page_idx,
                               slot_idx, quantize)
        new_k.append((kp, ks))
        new_v.append((vp, vs))
        attn = paged_decode_attention(
            q, kp, vp, page_table, lengths,
            k_scales=ks if quantize else None,
            v_scales=vs if quantize else None, interpret=interpret)
        h = h + _dense(attn.reshape(b, -1), out_w, out_b)
        ff = jax.nn.gelu(_dense(_ln(h, ln2_g, ln2_b), ff1_w, ff1_b))
        h = h + _dense(ff, ff2_w, ff2_b)
    logits = _head_logits(params, spec, h)            # (B, vocab)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    kv_out = (jnp.stack([k for k, _ in new_k]),
              jnp.stack([v for v, _ in new_v]),
              jnp.stack([s for _, s in new_k]),
              jnp.stack([s for _, s in new_v]))
    return next_tokens, logits, kv_out
