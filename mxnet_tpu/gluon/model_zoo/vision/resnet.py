"""ResNet V1/V2 for the model zoo.

Parity: python/mxnet/gluon/model_zoo/vision/resnet.py in the reference
(resnet18-152, v1 and v2 variants). resnet50_v1 is the framework's flagship
benchmark model (BASELINE.md); under hybridize the whole network lowers to
one XLA program with NCHW convs on the MXU.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, layout=layout)


def _bn_axis(layout):
    return 3 if layout == "NHWC" else 1


def _add_stem(features, channels0, thumbnail, stem, layout):
    """Append the shared input stem. stem='s2d' folds the stride-2 7x7
    into s2d(2)+4x4/1 with (2,1) pads (7 padded to 8) — exact for V1
    (tests/test_nhwc_layout.py); V2 rejects it because its input BatchNorm
    must see raw channels, not (offset, channel) subgrids."""
    ax = _bn_axis(layout)
    if thumbnail:
        features.add(_conv3x3(channels0, 1, 0, layout))
        return
    if stem == "s2d":
        # 224^2 RGB -> s2d(2) -> 112^2 x 12
        features.add(nn.Conv2D(channels0, 4, 1, ((2, 1), (2, 1)),
                               use_bias=False, in_channels=12,
                               layout=layout))
    else:
        features.add(nn.Conv2D(channels0, 7, 2, 3, use_bias=False,
                               layout=layout))
    features.add(nn.BatchNorm(axis=ax))
    features.add(nn.Activation("relu"))
    features.add(nn.MaxPool2D(3, 2, 1, layout=layout))


def _input_preamble(F, x, stem, layout):
    """NCHW API input -> internal layout (one transform at the graph edge)."""
    if stem == "s2d":
        x = F.space_to_depth(x, block_size=2)
    if layout == "NHWC":
        x = F.transpose(x, axes=(0, 2, 3, 1))
    return x


class BasicBlockV1(HybridBlock):
    """ResNet V1 basic block (model_zoo/vision/resnet.py:40)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    """ResNet V1 bottleneck (model_zoo/vision/resnet.py:84)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """ResNet V2 pre-activation basic block
    (model_zoo/vision/resnet.py:137)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """ResNet V2 pre-activation bottleneck
    (model_zoo/vision/resnet.py:191)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """ResNet V1 (model_zoo/vision/resnet.py:250).

    TPU-native extensions over the reference: layout='NHWC' runs the whole
    network channels-last (C on the MXU lane dimension; inputs stay NCHW at
    the API edge and are transposed once on entry), and stem='s2d' replaces
    the 7x7/2 stem conv with a space-to-depth(2) transform feeding a
    4x4/1 conv — 4x fewer stem HBM reads, the standard TPU ResNet trick
    (MLPerf). Both default off for reference parity."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem="conv7", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert stem in ("conv7", "s2d")
        assert not (thumbnail and stem == "s2d"), \
            "stem='s2d' replaces the 7x7 stem; thumbnail nets have none"
        self._layout = layout
        self._stem = stem
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_stem(self.features, channels[0], thumbnail, stem, layout)
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i], layout=layout))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = _input_preamble(F, x, self._stem, self._layout)
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """ResNet V2 (model_zoo/vision/resnet.py:318)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem="conv7", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert stem == "conv7", \
            "s2d stem is V1-only: V2's input BatchNorm must normalize raw " \
            "channels, and s2d before it would regroup them per pixel parity"
        self._layout = layout
        self._stem = stem
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False,
                                           axis=ax))
            _add_stem(self.features, channels[0], thumbnail, stem, layout)
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels, layout=layout))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = _input_preamble(F, x, self._stem, self._layout)
        x = self.features(x)
        return self.output(x)


# net depth -> (block spec, layers, channels)
resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=cpu(),
               root=None, **kwargs):
    """Constructor by (version, depth) (model_zoo/vision/resnet.py:385)."""
    assert num_layers in resnet_spec, \
        f"Invalid number of layers: {num_layers}. Options are {sorted(resnet_spec)}"
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2, \
        f"Invalid resnet version: {version}. Options are 1 and 2."
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise RuntimeError("pretrained weights are unavailable offline; "
                           "initialize() and train, or load_parameters()")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
