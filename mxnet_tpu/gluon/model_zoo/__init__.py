"""Model zoo (parity: python/mxnet/gluon/model_zoo/)."""
from . import vision
from . import transformer
from .transformer import TransformerBlock, TransformerLM, transformer_lm
