"""Gluon — the imperative high-level API (parity: python/mxnet/gluon/).

Blocks run eagerly for debuggability; ``hybridize()`` compiles the whole
forward/backward into one XLA executable (see block.py for the TPU redesign
of CachedOp).
"""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import utils
from . import parameter
from . import block


def __getattr__(name):
    import importlib

    if name in ("rnn", "data", "model_zoo", "contrib"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
