"""Gluon DataLoader.

Parity: python/mxnet/gluon/data/dataloader.py:533. TPU redesign: workers are
threads feeding a host-side prefetch queue of numpy batches (JPEG decode and
augmentation release the GIL via numpy/PIL), and the final device_put
overlaps with TPU compute — the reference's fork-based multiprocess pool +
shared-memory NDArray pickling (dataloader.py:134-156) existed to dodge the
Python GIL for CPU-bound OpenCV augmentation and to share buffers with the
engine process; with PJRT the host→HBM copy is already async so thread
workers + pinned-free numpy staging deliver the same overlap with far less
machinery. num_workers>0 therefore maps to a thread pool.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ... import ndarray as nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py:127)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


class DataLoader:
    """Loads data from a Dataset and returns mini-batches."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch_idx])
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        """Ordered prefetch over a thread pool (see module docstring)."""
        batches = list(self._batch_sampler)
        results: dict[int, object] = {}
        lock = threading.Lock()
        cond = threading.Condition(lock)
        next_submit = [0]
        depth = self._prefetch or (2 * self._num_workers)
        errors: list[BaseException] = []

        def work(job):
            j, batch_idx = job
            try:
                out = self._batchify_fn([self._dataset[i] for i in batch_idx])
            except BaseException as e:  # propagate to consumer
                with cond:
                    errors.append(e)
                    cond.notify_all()
                return
            with cond:
                results[j] = out
                cond.notify_all()

        jobs = queue.Queue()
        for j, b in enumerate(batches):
            jobs.put((j, b))

        def worker_loop():
            while True:
                try:
                    job = jobs.get_nowait()
                except queue.Empty:
                    return
                # throttle: don't run too far ahead of the consumer
                with cond:
                    while job[0] > next_submit[0] + depth and not errors:
                        cond.wait(0.05)
                    if errors:
                        return
                work(job)

        threads = [threading.Thread(target=worker_loop, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for j in range(len(batches)):
                with cond:
                    while j not in results and not errors:
                        if not cond.wait(self._timeout):
                            raise RuntimeError(
                                f"DataLoader timed out after {self._timeout}s "
                                f"waiting for batch {j}")
                    if errors:
                        raise errors[0]
                    out = results.pop(j)
                    next_submit[0] = j + 1
                    cond.notify_all()
                yield out
        finally:
            with cond:
                errors.append(StopIteration())
                cond.notify_all()
            for t in threads:
                t.join(timeout=1.0)
