"""Gluon DataLoader.

Parity: python/mxnet/gluon/data/dataloader.py:533. Two worker modes:

- ``thread_pool=True``: threads feeding a host-side prefetch queue (JPEG
  decode and numpy augmentation release the GIL), final device_put overlaps
  with TPU compute.
- ``thread_pool=False`` (default, reference semantics): fork-based worker
  PROCESSES with shared-memory batch transport — the counterpart of the
  reference's multiprocess pool + shm NDArray pickling
  (dataloader.py:134-156). Pure-Python Dataset transforms that hold the
  GIL scale across cores this way. Workers run host-side numpy only
  (never the jax/TPU client — a forked PJRT client is unusable), so in
  process mode samples/batches must be numpy; device conversion happens
  in the parent.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ... import ndarray as nd
from ...observability import trace as _obs_trace
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "numpy_batchify_fn",
           "stats", "reset_stats"]

# Resilience observability: worker respawns survive the local warning and
# surface in profiler.dispatch_stats() next to the watchdog/elastic
# counters, so one call reports every resilience event (docs/resilience.md).
_STATS = {"dataloader_respawns": 0}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py:127)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


def numpy_batchify_fn(data):
    """Stack samples into numpy batches — the worker-process form of
    default_batchify_fn (no device arrays in forked children)."""
    if isinstance(data[0], tuple):
        return tuple(numpy_batchify_fn(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _to_device(batch):
    if isinstance(batch, tuple):
        return tuple(_to_device(b) for b in batch)
    return nd.array(batch)


def _shm_export(batch, shms):
    """Copy a numpy batch (array or tuple tree) into SharedMemory blocks;
    returns a picklable descriptor. The reference pickles NDArrays through
    shared memory the same way (dataloader.py:134-156)."""
    from multiprocessing import shared_memory

    if isinstance(batch, tuple):
        return ("tuple", [_shm_export(b, shms) for b in batch])
    arr = np.ascontiguousarray(batch)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    shms.append(shm)
    view = np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)
    view[...] = arr
    return ("array", shm.name, arr.shape, str(arr.dtype))


def _shm_import(desc):
    """Materialize a descriptor into numpy copies and release the blocks."""
    from multiprocessing import shared_memory

    if desc[0] == "tuple":
        return tuple(_shm_import(d) for d in desc[1])
    _, name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    try:
        out = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf).copy()
    finally:
        shm.close()
        shm.unlink()
    return out


def _shm_discard(desc):
    """Unlink an un-consumed descriptor's blocks (abandoned iterator)."""
    from multiprocessing import shared_memory

    if desc[0] == "tuple":
        for d in desc[1]:
            _shm_discard(d)
        return
    try:
        shm = shared_memory.SharedMemory(name=desc[1])
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass


def _mp_worker(dataset, batchify_fn, job_q, result_q):
    """Worker-process loop: fetch index lists, batchify with numpy, ship
    through shared memory. Runs no jax."""
    while True:
        job = job_q.get()
        if job is None:
            return
        j, batch_idx = job
        shms = []
        try:
            out = batchify_fn([dataset[i] for i in batch_idx])
            desc = _shm_export(out, shms)
            result_q.put((j, "ok", desc))
            for shm in shms:
                shm.close()
        except BaseException as e:  # noqa: BLE001 - propagate to parent
            import traceback

            # a partial export (e.g. /dev/shm exhaustion mid-batch) must not
            # leak the segments already created for this job
            for shm in shms:
                try:
                    shm.close()
                    shm.unlink()
                except OSError:
                    pass
            result_q.put((j, "error",
                          f"{type(e).__name__}: {e}\n"
                          f"{traceback.format_exc()}"))


class DataLoader:
    """Loads data from a Dataset and returns mini-batches."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120,
                 max_worker_respawns=None):
        import os as _os

        self._dataset = dataset
        self._timeout = timeout
        if max_worker_respawns is None:
            max_worker_respawns = int(_os.environ.get(
                "MXNET_TPU_DATALOADER_RESPAWNS", str(max(1, num_workers))))
        self._max_worker_respawns = max(0, max_worker_respawns)
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._thread_pool = thread_pool
        self._num_workers = max(0, num_workers)
        self._mp = self._num_workers > 0 and not thread_pool
        if batchify_fn is None:
            batchify_fn = numpy_batchify_fn if self._mp \
                else default_batchify_fn
        self._batchify_fn = batchify_fn
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        # each next() is spanned as the step timeline's data-wait phase:
        # the time the training loop stalls on input, not the time the
        # consumer spends using the batch (docs/observability.md)
        it = self._iter_impl()
        while True:
            with _obs_trace.span("step.data_wait"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    def _iter_impl(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch_idx])
            return
        if self._mp and self._fork_safe():
            yield from self._mp_iter()
        else:
            yield from self._threaded_iter()

    def _fork_safe(self):
        """Process workers must never touch the jax client a fork
        inherited — datasets yielding device NDArrays run on the thread
        pool instead (probe one sample once)."""
        if not hasattr(self, "_fork_ok"):
            def any_nd(x):
                if isinstance(x, nd.NDArray):
                    return True
                if isinstance(x, (tuple, list)):
                    return any(any_nd(i) for i in x)
                return False

            self._fork_ok = len(self._dataset) == 0 or \
                not any_nd(self._dataset[0])
            if not self._fork_ok:
                import warnings

                warnings.warn(
                    "DataLoader: dataset yields device NDArrays, which "
                    "cannot cross a fork — falling back to thread workers. "
                    "Return numpy from the Dataset (or pass "
                    "thread_pool=True) to silence this.")
                if self._batchify_fn is numpy_batchify_fn:
                    self._batchify_fn = default_batchify_fn
        return self._fork_ok

    def _mp_iter(self):
        """Fork worker processes; batches return via shared memory and are
        converted to device arrays in the parent (reference multiprocess
        DataLoader semantics, dataloader.py:533).

        Robustness: a worker that dies mid-epoch (OOM-kill, segfault) is
        respawned — up to ``max_worker_respawns`` times — and any batch
        it may have taken to its grave is resubmitted (duplicate results
        from requeue races are detected and their shared memory
        reclaimed). The result poll is bounded by ``timeout`` per batch
        and raises naming the dead worker instead of stalling forever.
        """
        import multiprocessing as mp
        import time as _time

        ctx = mp.get_context("fork")
        job_q = ctx.Queue()
        result_q = ctx.Queue()

        def spawn():
            w = ctx.Process(target=_mp_worker,
                            args=(self._dataset, self._batchify_fn,
                                  job_q, result_q), daemon=True)
            w.start()
            return w

        workers = [spawn() for _ in range(self._num_workers)]
        batches = list(self._batch_sampler)
        pending: dict[int, object] = {}
        received: set[int] = set()
        respawns = [0]

        def accept(got_j, status, payload):
            """Record one result; duplicates (from requeue races) are
            dropped — including failing duplicates of a batch whose
            original result already arrived."""
            if got_j in received:
                if status == "ok":
                    _shm_discard(payload)
                return
            if status == "error":
                raise RuntimeError(
                    f"DataLoader worker failed on batch {got_j}: "
                    f"{payload}")
            received.add(got_j)
            pending[got_j] = payload

        def reap_and_respawn(waiting_for, submitted):
            """Replace dead workers and resubmit possibly-lost jobs."""
            dead = [w for w in workers if not w.is_alive()]
            if not dead:
                return
            for w in dead:
                info = f"pid {w.pid}, exitcode {w.exitcode}"
                if respawns[0] >= self._max_worker_respawns:
                    raise RuntimeError(
                        f"DataLoader worker ({info}) died while producing "
                        f"batch ~{waiting_for} and the respawn budget "
                        f"({self._max_worker_respawns}) is exhausted; "
                        "check the dataset __getitem__ for crashes/OOM, "
                        "or raise max_worker_respawns")
                respawns[0] += 1
                _STATS["dataloader_respawns"] += 1
                workers[workers.index(w)] = spawn()
                import warnings

                warnings.warn(
                    f"DataLoader worker ({info}) died mid-epoch; "
                    f"respawned (respawn {respawns[0]}/"
                    f"{self._max_worker_respawns})")
            # drain already-delivered results first so only genuinely
            # missing jobs get resubmitted
            while True:
                try:
                    accept(*result_q.get_nowait())
                except queue.Empty:
                    break
            # a submitted-but-undelivered job may have been lost inside a
            # dead worker: resubmit those (ones still sitting untaken in
            # job_q get recomputed as duplicates — rare, bounded by the
            # prefetch depth, and deduped on receive)
            for i in range(waiting_for, submitted):
                if i not in received:
                    job_q.put((i, batches[i]))

        try:
            depth = min(len(batches),
                        self._prefetch or 2 * self._num_workers)
            submitted = 0
            for submitted in range(depth):
                job_q.put((submitted, batches[submitted]))
            submitted = depth
            for j in range(len(batches)):
                deadline = _time.monotonic() + self._timeout
                while j not in pending:
                    try:
                        got = result_q.get(timeout=1.0)
                    except queue.Empty:
                        reap_and_respawn(j, submitted)
                        if _time.monotonic() > deadline:
                            states = ", ".join(
                                f"pid {w.pid}: "
                                f"{'alive' if w.is_alive() else f'dead (exitcode {w.exitcode})'}"
                                for w in workers)
                            raise RuntimeError(
                                f"DataLoader timed out after "
                                f"{self._timeout}s waiting for batch {j} "
                                f"(workers: {states}); raise timeout= or "
                                "check the dataset for a hang")
                        continue
                    accept(*got)
                if submitted < len(batches):
                    job_q.put((submitted, batches[submitted]))
                    submitted += 1
                yield _to_device(_shm_import(pending.pop(j)))
        finally:
            for _ in workers:
                job_q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
            # reclaim shared memory of batches never consumed (abandoned
            # iterator / error path): drain the queue, then pending
            while True:
                try:
                    _, status, payload = result_q.get(timeout=0.2)
                except (queue.Empty, OSError):
                    break
                if status == "ok":
                    _shm_discard(payload)
            for desc in pending.values():
                _shm_discard(desc)

    def _threaded_iter(self):
        """Ordered prefetch over a thread pool (see module docstring)."""
        batches = list(self._batch_sampler)
        results: dict[int, object] = {}
        lock = threading.Lock()
        cond = threading.Condition(lock)
        next_submit = [0]
        depth = self._prefetch or (2 * self._num_workers)
        errors: list[BaseException] = []

        def work(job):
            j, batch_idx = job
            try:
                out = self._batchify_fn([self._dataset[i] for i in batch_idx])
            except BaseException as e:  # propagate to consumer
                with cond:
                    errors.append(e)
                    cond.notify_all()
                return
            with cond:
                results[j] = out
                cond.notify_all()

        jobs = queue.Queue()
        for j, b in enumerate(batches):
            jobs.put((j, b))

        def worker_loop():
            while True:
                try:
                    job = jobs.get_nowait()
                except queue.Empty:
                    return
                # throttle: don't run too far ahead of the consumer
                with cond:
                    while job[0] > next_submit[0] + depth and not errors:
                        cond.wait(0.05)
                    if errors:
                        return
                work(job)

        threads = [threading.Thread(target=worker_loop, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for j in range(len(batches)):
                with cond:
                    while j not in results and not errors:
                        if not cond.wait(self._timeout):
                            raise RuntimeError(
                                f"DataLoader timed out after {self._timeout}s "
                                f"waiting for batch {j}")
                    if errors:
                        raise errors[0]
                    out = results.pop(j)
                    next_submit[0] = j + 1
                    cond.notify_all()
                yield out
        finally:
            with cond:
                errors.append(StopIteration())
                cond.notify_all()
            for t in threads:
                t.join(timeout=1.0)
