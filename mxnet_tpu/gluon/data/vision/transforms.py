"""Vision transforms.

Parity: python/mxnet/gluon/data/vision/transforms.py (Compose, ToTensor,
Normalize, Resize, crops, flips, ...). Transforms are host-side (numpy) —
the TPU analogue of the reference's CPU augmenter chain; heavy per-batch
math belongs in the jitted step instead.
"""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from .... import ndarray as nd

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomCrop",
           "RandomFlipLeftRight", "RandomFlipTopBottom", "RandomBrightness",
           "RandomContrast", "RandomSaturation", "RandomLighting"]


class Compose(Sequential):
    """Sequentially composes multiple transforms
    (vision/transforms.py:34)."""

    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            for t in transforms:
                self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1) (vision/transforms.py:89)."""

    def hybrid_forward(self, F, x):
        if len(x.shape) == 4:
            out = F.transpose(x, axes=(0, 3, 1, 2))
        else:
            out = F.transpose(x, axes=(2, 0, 1))
        return F.Cast(out, dtype="float32") / 255.0


class Normalize(HybridBlock):
    """Channel-wise (x - mean) / std on CHW float input
    (vision/transforms.py:131)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, dtype=np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        mean = nd.array(self._mean)
        std = nd.array(self._std)
        return (x - mean) / std

    def hybrid_forward(self, F, x):
        return self.forward(x)


def _to_np(x):
    return x.asnumpy() if isinstance(x, nd.NDArray) else np.asarray(x)


class Resize(Block):
    """Resize to a given size with bilinear interpolation
    (vision/transforms.py:183)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._keep = keep_ratio

    def forward(self, x):
        from ....image import imresize
        return imresize(x, self._size[0], self._size[1])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        a = _to_np(x)
        h, w = a.shape[:2]
        cw, ch = self._size
        y0 = max(0, (h - ch) // 2)
        x0 = max(0, (w - cw) // 2)
        return nd.array(a[y0:y0 + ch, x0:x0 + cw])


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad

    def forward(self, x):
        a = _to_np(x)
        if self._pad:
            p = self._pad
            a = np.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        h, w = a.shape[:2]
        cw, ch = self._size
        y0 = np.random.randint(0, max(1, h - ch + 1))
        x0 = np.random.randint(0, max(1, w - cw + 1))
        return nd.array(a[y0:y0 + ch, x0:x0 + cw])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from ....image import imresize
        a = _to_np(x)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            ar = np.exp(np.random.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                x0 = np.random.randint(0, w - cw + 1)
                y0 = np.random.randint(0, h - ch + 1)
                crop = a[y0:y0 + ch, x0:x0 + cw]
                return imresize(nd.array(crop), self._size[0], self._size[1])
        return imresize(nd.array(a), self._size[0], self._size[1])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd.array(_to_np(x)[:, ::-1].copy())
        return x if isinstance(x, nd.NDArray) else nd.array(x)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd.array(_to_np(x)[::-1].copy())
        return x if isinstance(x, nd.NDArray) else nd.array(x)


class _RandomColorJitterBase(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def _alpha(self):
        return 1.0 + np.random.uniform(-self._b, self._b)


class RandomBrightness(_RandomColorJitterBase):
    def forward(self, x):
        a = _to_np(x).astype(np.float32) * self._alpha()
        return nd.array(a)


class RandomContrast(_RandomColorJitterBase):
    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        coef = np.array([0.299, 0.587, 0.114], dtype=np.float32)
        alpha = self._alpha()
        gray = (a * coef).sum() * (1.0 - alpha) / a[..., :1].size
        return nd.array(a * alpha + gray)


class RandomSaturation(_RandomColorJitterBase):
    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        coef = np.array([0.299, 0.587, 0.114], dtype=np.float32)
        alpha = self._alpha()
        gray = (a * coef).sum(axis=-1, keepdims=True) * (1.0 - alpha)
        return nd.array(a * alpha + gray)


class RandomLighting(Block):
    """AlexNet-style PCA noise (vision/transforms.py:580)."""

    _eigval = np.array([55.46, 4.794, 1.148], dtype=np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        alpha = np.random.normal(0, self._alpha, size=(3,)).astype(np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd.array(a + rgb)
