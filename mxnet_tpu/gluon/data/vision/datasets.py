"""Vision datasets.

Parity: python/mxnet/gluon/data/vision/datasets.py (MNIST, FashionMNIST,
CIFAR10/100, ImageRecordDataset, ImageFolderDataset). This environment has
no network egress, so the download path only serves pre-cached files; a
deterministic synthetic fallback (MXNET_TPU_SYNTH_DATA=1) keeps training
examples and tests runnable without the real archives.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .. import dataset
from ....import ndarray as nd

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synth_ok():
    return os.environ.get("MXNET_TPU_SYNTH_DATA", "1") != "0"


class _DownloadedDataset(dataset.Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST handwritten digits (gluon/data/vision/datasets.py:36)."""

    _n = 60000
    _shape = (28, 28, 1)
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = ("train-images-idx3-ubyte.gz",)
        self._train_label = ("train-labels-idx1-ubyte.gz",)
        self._test_data = ("t10k-images-idx3-ubyte.gz",)
        self._test_label = ("t10k-labels-idx1-ubyte.gz",)
        super().__init__(root, transform)

    def _get_data(self):
        files = (self._train_data[0], self._train_label[0]) if self._train \
            else (self._test_data[0], self._test_label[0])
        data_file = os.path.join(self._root, files[0])
        label_file = os.path.join(self._root, files[1])
        if os.path.exists(data_file) and os.path.exists(label_file):
            with gzip.open(label_file, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
            with gzip.open(data_file, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = np.frombuffer(fin.read(), dtype=np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
        elif _synth_ok():
            n = 2048 if self._train else 512
            rng = np.random.RandomState(42 if self._train else 43)
            label = rng.randint(0, self._nclass, n).astype(np.int32)
            # class-dependent blobs so models can actually learn
            data = (rng.rand(n, *self._shape) * 64).astype(np.uint8)
            for i, l in enumerate(label):
                data[i, 2 + l * 2:6 + l * 2, 4:24, 0] = 255
        else:
            raise RuntimeError(
                f"MNIST files not found under {self._root} and synthetic "
                "fallback disabled (MXNET_TPU_SYNTH_DATA=0)")
        self._label = label
        self._data = nd.array(data, dtype=np.uint8)


class FashionMNIST(MNIST):
    """FashionMNIST clothing dataset (same format as MNIST)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 image classification (gluon/data/vision/datasets.py:126)."""

    _nclass = 10
    _pickle_names = None

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            files = [os.path.join(self._root, f"data_batch_{i}.bin")
                     for i in range(1, 6)]
        else:
            files = [os.path.join(self._root, "test_batch.bin")]
        if all(os.path.exists(f) for f in files):
            data, label = zip(*[self._read_batch(f) for f in files])
            data = np.concatenate(data)
            label = np.concatenate(label)
        elif _synth_ok():
            n = 2048 if self._train else 512
            rng = np.random.RandomState(7 if self._train else 8)
            label = rng.randint(0, self._nclass, n).astype(np.int32)
            data = (rng.rand(n, 32, 32, 3) * 64).astype(np.uint8)
            for i, l in enumerate(label):
                data[i, :, l * 3:l * 3 + 3, :] = 200
        else:
            raise RuntimeError(
                f"CIFAR10 files not found under {self._root} and synthetic "
                "fallback disabled")
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    """CIFAR100 (gluon/data/vision/datasets.py:171)."""

    _nclass = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3072 + 2)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 + self._fine_label].astype(np.int32)

    def _get_data(self):
        name = "train.bin" if self._train else "test.bin"
        f = os.path.join(self._root, name)
        if os.path.exists(f):
            self._data_np, self._label = self._read_batch(f)
            self._data = nd.array(self._data_np, dtype=np.uint8)
            return
        super()._get_data()


class ImageRecordDataset(dataset.RecordFileDataset):
    """Dataset wrapping a RecordIO file of images
    (gluon/data/vision/datasets.py:217)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = super().__getitem__(idx)
        header, img = unpack_img(record, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(nd.array(img), label)
        return nd.array(img), label


class ImageFolderDataset(dataset.Dataset):
    """A dataset loading image files from a folder hierarchy
    (gluon/data/vision/datasets.py:257): root/category/image.ext"""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
