"""Gluon Trainer — applies an Optimizer to a set of Parameters.

Parity: python/mxnet/gluon/trainer.py:28 in the reference (step :320,
_allreduce_grads :371, _update :430). TPU redesign: on 'tpu'/'dist' kvstores
the gradient allreduce is a psum that XLA lowers onto ICI when the step runs
inside a pjit-ed mesh program (see mxnet_tpu/parallel); the single-process
update path runs the fused optimizer ops so the whole step can live in one
jitted executable.
"""
from __future__ import annotations

import os
import warnings

from .. import optimizer as opt
from .. import kvstore as kvs
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from .parameter import Parameter
from ..ndarray import NDArray

__all__ = ["Trainer"]



class Trainer:
    """Applies an Optimizer on a set of Parameters."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._states_to_init = False
        self._sentinel = None  # set by resilience.HealthSentinel.attach

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore:
            kv = kvs.create(kvstore) if isinstance(kvstore, str) else kvstore
            if update_on_kvstore is None:
                update_on_kvstore = False
            self._kvstore = kv
            self._update_on_kvstore = update_on_kvstore
            if update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(i, param.data())
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Makes one step of parameter update: allreduce grads then apply
        the optimizer (trainer.py:320). An attached HealthSentinel is
        consulted between the allreduce and the (possibly bulked) update,
        so an unhealthy batch never reaches the weights. The whole sweep
        runs under the step watchdog (MXNET_TPU_WATCHDOG_STEP_TIMEOUT):
        a stall raises StallError — or, with a rollback-policy sentinel
        attached, resumes from the last good checkpoint instead."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        try:
            with _watchdog.guard(
                    "step", detail="gluon.Trainer.step",
                    step=getattr(self._sentinel, "_step", None)):
                self._update_phases(ignore_stale_grad, allreduce=True)
        except _watchdog.PeerLostError:
            raise  # a dead peer won't come back next step: rolling back
            # and retrying would spin forever; surface the rank instead
        except _watchdog.StallError as e:
            if not self._stall_rollback(e):
                raise

    def _stall_rollback(self, err):
        """A stalled step can resume from the last good checkpoint when a
        rollback-policy sentinel (with a CheckpointManager) is attached:
        restore params+optimizer+RNG+scaler, amend the crash report with
        the restored manifest, and report the step as skipped. Returns
        True when the stall was recovered."""
        s = self._sentinel
        if s is None or s.policy != "rollback" or s.manager is None:
            return False
        manifest = s.manager.restore_latest(net=s._net, trainer=self)
        if manifest is None:
            return False
        _watchdog.note_rollback(err, manifest)
        import warnings

        warnings.warn(
            f"training step stalled ({err}); rolled back to checkpoint "
            f"step {manifest.get('step')} and skipped the step")
        return True

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                grad = param.grad()
                self._kvstore.push(i, grad)
                if not self._update_on_kvstore:
                    self._kvstore.pull(i, grad)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        assert not self._update_on_kvstore, \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._optimizer.rescale_grad = self._scale / batch_size
        try:
            with _watchdog.guard(
                    "step", detail="gluon.Trainer.update",
                    step=getattr(self._sentinel, "_step", None)):
                self._update_phases(ignore_stale_grad, allreduce=False)
        except _watchdog.PeerLostError:
            raise  # see step(): dead peers are not transient stalls
        except _watchdog.StallError as e:
            if not self._stall_rollback(e):
                raise

    def _update_phases(self, ignore_stale_grad, allreduce):
        """The guarded step body, shared by step() and update(), with
        each phase under a trace span (docs/observability.md): one
        training step yields a phase-labeled ``train.step`` timeline —
        allreduce, sentinel check, optimizer sweep."""
        with _obs_trace.span("train.step",
                             entry="step" if allreduce else "update",
                             step=getattr(self._sentinel, "_step", None)):
            _faults.maybe_hang("hang_step")
            if allreduce:
                with _obs_trace.span("step.allreduce"):
                    self._allreduce_grads()
            _faults.maybe_nan_grads(self._params)
            _faults.maybe_nonfinite_grad(self._params)
            if self._sentinel is not None:
                with _obs_trace.span("step.sentinel"):
                    healthy = self._sentinel.before_update(self)
                if not healthy:
                    return  # skipped or rolled back per the sentinel policy
            with _obs_trace.span("step.update"):
                self._update(ignore_stale_grad)

    def _bulk_size(self):
        """Ops to bulk per lazy segment during _update (0 = eager).
        MXNET_TPU_BULK_OPT_UPDATES=<n> (read per step, so it can be set
        after import) forces bulking for every Trainer; otherwise it
        engages only when the optimizer sets aggregate_num > 1
        (docs/engine.md)."""
        env = os.environ.get("MXNET_TPU_BULK_OPT_UPDATES")
        if env:
            try:
                n = int(env)
            except ValueError:
                n = None
            if n is not None:
                return n if n > 0 else 0  # explicit 0/-1 = kill switch
        agg = getattr(self._optimizer, "aggregate_num", 0)
        return agg if agg and agg > 1 else 0

    def _update(self, ignore_stale_grad=False):
        updates = [[] for _ in self._updaters]
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore:
                self._kvstore.pull(i, param.data())
                continue
            for upd, arr, grad in zip(updates, param.list_data(),
                                      param.list_grad()):
                upd.append((i, grad, arr))
        if not self._update_on_kvstore:
            bulk_n = self._bulk_size()
            if bulk_n:
                # record the whole update sweep into lazy segments so the
                # per-parameter update ops compile/launch as fused bundles
                from .. import engine

                with engine.bulk(bulk_n):
                    for updater, upd in zip(self._updaters, updates):
                        for i, g, w in upd:
                            updater(i, g, w)
            else:
                for updater, upd in zip(self._updaters, updates):
                    for i, g, w in upd:
                        updater(i, g, w)

    def get_states_bytes(self):
        """Serialized trainer states (optimizer state per parameter) —
        the byte form consumed by resilience.CheckpointManager."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        return self._updaters[0].get_states(
            dump_optimizer=self._update_on_kvstore)

    def set_states_bytes(self, states):
        """Inverse of get_states_bytes (bitwise round-trip)."""
        if not self._kv_initialized:
            self._init_kvstore()
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}

    def save_states(self, fname):
        """Saves trainer states (optimizer + scheduler) to a file
        (trainer.py:463). Atomic: temp file + fsync + rename, so a crash
        mid-write can never truncate an existing states file."""
        from ..resilience.checkpoint import atomic_write_bytes

        atomic_write_bytes(fname, self.get_states_bytes())

    def load_states(self, fname):
        """Loads trainer states from a file (trainer.py:492)."""
        with open(fname, "rb") as f:
            states = f.read()
        self.set_states_bytes(states)
