"""gluon.contrib.nn — auxiliary blocks.

Capability parity with python/mxnet/gluon/contrib/nn/basic_layers.py:
Concurrent/HybridConcurrent (parallel branches, concatenated),
Identity, SparseEmbedding, SyncBatchNorm.
"""
from __future__ import annotations

import warnings

from .. import nn as _nn
from ..block import Block, HybridBlock

__all__ = ["Remat", "Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]


class Concurrent(_nn.Sequential):
    """Feed input to every child, concat outputs along `axis`."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd

        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(_nn.HybridSequential):
    """Hybridizable Concurrent. HybridSequential short-circuits its children
    chain in _call_with_params / the Symbol path, so both are overridden
    here to concatenate instead."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def _concat(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)

    def hybrid_forward(self, F, x):
        return self._concat(F, x)

    def _call_with_params(self, *args):
        from ... import ndarray as F

        return self._concat(F, args[0])

    def forward(self, x, *args):
        from ... import symbol as _sym
        from ...symbol import Symbol

        if isinstance(x, Symbol):
            return self._concat(_sym, x)
        return HybridBlock.forward(self, x, *args)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """API parity for contrib.nn.SparseEmbedding: on TPU the dense-gradient
    Embedding is the efficient path (XLA scatter-add), so this delegates
    and documents the difference."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        warnings.warn("SparseEmbedding uses dense gradients on TPU "
                      "(row_sparse grads are a GPU/PS optimization)")
        with self.name_scope():
            self._embed = _nn.Embedding(input_dim, output_dim, dtype=dtype,
                                        weight_initializer=weight_initializer)

    def forward(self, x):
        return self._embed(x)


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device BatchNorm (contrib SyncBatchNorm / sync_batch_norm.cc).
    Under GSPMD the batch axis is sharded over the mesh and XLA computes
    batch statistics with cross-replica collectives automatically, so the
    standard BatchNorm IS synchronized; this subclass exists for API
    parity (num_devices is accepted and ignored)."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)


class Remat(HybridBlock):
    """Segment-level activation rematerialization around any block.

    Inside a functional trace (ShardedTrainer / parallel.functional_call —
    the compiled-training paths, where parameter cells hold jax tracers)
    the wrapped block runs under ``jax.checkpoint``: its internal
    activations are recomputed during the backward instead of kept —
    the segment-granular form of the reference's gradient mirroring
    (src/nnvm/gradient.cc:107-148). In plain eager mode and under
    hybridize's discovery trace (where cells hold concrete values that
    must be *captured*, not baked in) it is a transparent pass-through.

    Example::

        stage = contrib.nn.Remat(resnet_stage)   # per-stage remat
    """

    def __init__(self, block, policy=None, **kwargs):
        super().__init__(**kwargs)
        from ...remat import resolve_policy
        with self.name_scope():
            self.block = block
        self._policy = resolve_policy(policy)

    def forward(self, *args):
        from ...jit import _active, _notify_io, _notify_mutation
        from ...ndarray.ndarray import NDArray

        if _active() is None:  # eager: no compiled backward to remat
            return self.block(*args)

        import jax

        # only checkpoint when the cells are already functional (tracers):
        # in a TracedFunction discovery run the cells hold concrete arrays
        # and reading them here would bake weights into the compiled cache
        # as constants — pass through and let the tape capture them
        cell_vals = [p.data().data_
                     for p in self.block.collect_params().values()]
        cell_vals += [a.data_ for a in args if isinstance(a, NDArray)]
        if not any(isinstance(v, jax.core.Tracer) for v in cell_vals):
            return self.block(*args)

        from ... import autograd
        from ...parallel.functional import (
            functional_call, param_arrays, aux_arrays, RNG_KEY)
        from ... import random as _random

        fn = functional_call(self.block, train=autograd.is_training())
        pvals = param_arrays(self.block)
        avals = aux_arrays(self.block)
        xs = [a.data_ if isinstance(a, NDArray) else a for a in args]
        out, new_aux = jax.checkpoint(fn, policy=self._policy)(
            pvals, avals, *xs)
        # surface the sub-block's aux mutations (BN stats, rng key) to the
        # enclosing trace session
        cells = {name: p.data()
                 for name, p in self.block.collect_params().items()}
        for name, val in new_aux.items():
            if name == RNG_KEY:
                cell = _random.generator_key()
            else:
                cell = cells[name]
            cell._data = val
            _notify_mutation(cell)
        outs = ([NDArray(o) for o in out] if isinstance(out, tuple)
                else [NDArray(out)])
        _notify_io([a for a in args if isinstance(a, NDArray)], outs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def hybrid_forward(self, F, *args):  # pragma: no cover - forward() used
        return self.block(*args)


class MultiHeadAttention(HybridBlock):
    """Multi-head attention block with a selectable attention kernel —
    the Block-API door to the framework's best attention paths (round-5:
    previously the Pallas kernel was reachable only through
    parallel.attention, invisible to gluon models).

    impl:
      - 'dense': fused XLA composition (differentiable, any backend)
      - 'flash': Pallas streaming kernel, O(T) HBM, trainable via
        custom_vjp (ops/pallas_kernels.flash_attention_with_grad)
      - 'ring':  sequence-parallel ring attention over `mesh`'s
        `sp_axis` (parallel/ring_attention.py) — for T beyond one chip
      - 'auto':  picks per shape/backend (parallel.attention)

    Self-attention: ``block(x)`` with x (B, L, units). Cross-attention:
    ``block(x, key_value)`` with key_value (B, S, units) — q projects
    from x, k/v from key_value (the reference's encdec interleaved
    layout, contrib/transformer.cc:736-819). Output (B, L, units).
    """

    def __init__(self, units, num_heads, impl="dense", causal=False,
                 use_bias=True, mesh=None, sp_axis="sp", dtype=None,
                 cross_attention=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by num_heads "
                             f"{num_heads}")
        self._units = units
        self._heads = num_heads
        self._impl = impl
        self._causal = causal
        self._mesh = mesh
        self._sp_axis = sp_axis
        with self.name_scope():
            if cross_attention:
                # q from the query stream, interleaved k/v from the
                # key_value stream (the encdec layout); created only on
                # request so self-attention blocks don't carry ~3·units²
                # dead parameters
                self.q_proj = _nn.Dense(units, use_bias=use_bias,
                                        flatten=False, in_units=units,
                                        prefix="q_")
                self.kv_proj = _nn.Dense(2 * units, use_bias=use_bias,
                                         flatten=False, in_units=units,
                                         prefix="kv_")
                self.qkv_proj = None
            else:
                self.qkv_proj = _nn.Dense(3 * units, use_bias=use_bias,
                                          flatten=False, prefix="qkv_")
                self.q_proj = self.kv_proj = None
            self.out_proj = _nn.Dense(units, use_bias=use_bias,
                                      flatten=False, prefix="out_")

    def _split_heads(self, F, x, n):
        # (B, L, n*units) -> n tensors (B, H, L, d)
        b_l_u = x.shape
        h, d = self._heads, self._units // self._heads
        x = F.reshape(x, shape=(b_l_u[0], b_l_u[1], n * h, d))
        x = F.transpose(x, axes=(0, 2, 1, 3))  # (B, n*H, L, d)
        return [F.slice_axis(x, axis=1, begin=i * h, end=(i + 1) * h)
                for i in range(n)]

    def hybrid_forward(self, F, x, key_value=None):
        if key_value is None:
            if self.qkv_proj is None:
                raise ValueError("this block was built with "
                                 "cross_attention=True; pass key_value")
            q, k, v = self._split_heads(F, self.qkv_proj(x), 3)
        else:
            if self.q_proj is None:
                raise ValueError("pass cross_attention=True at construction "
                                 "for the cross-attention path")
            (q,) = self._split_heads(F, self.q_proj(x), 1)
            k, v = self._split_heads(F, self.kv_proj(key_value), 2)
        if self._impl in ("dense", "flash"):
            out = F.scaled_dot_product_attention(
                q, k, v, causal=self._causal, impl=(
                    "flash" if self._impl == "flash" else "xla"))
        elif self._impl in ("ring", "auto"):
            from ... import parallel

            # per-hop kernel: 'auto' picks the Pallas flash kernel on TPU
            # and the dense composition on CPU meshes (virtual-device CI)
            out = parallel.attention(q, k, v, causal=self._causal,
                                     mesh=self._mesh,
                                     axis_name=self._sp_axis, impl="auto")
        else:
            raise ValueError(f"unknown impl {self._impl!r}")
        b, h, l, d = out.shape
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(b, l, h * d))
        return self.out_proj(out)
